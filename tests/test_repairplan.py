"""Repair-locality planner (round 14): code-family-aware minimal
helper sets, sub-chunk wire reads, cost-biased selection, and the
range-integrity ladder.

Bit-exactness contract: a planner-driven rebuild (local-group LRC
decode, Clay repair-plane range reads, SHEC window reads) must produce
EXACTLY the bytes the full-decode oracle produces — in both integrity
modes (device fold and host-crc) — while moving fewer helper bytes.
"""

import numpy as np
import pytest

from ceph_tpu.ec.registry import factory
from ceph_tpu.osd.ecbackend import (ECBackend, RecoveryRunner, ShardSet,
                                    shard_cid)
from ceph_tpu.osd.memstore import Transaction
from ceph_tpu.osd.repairplan import (coalesce_ranges, plan_read,
                                     plan_repair)


def _host_crc_params():
    from ceph_tpu.osd.ecbackend import _host_crc_available
    return [False, True] if _host_crc_available() else [False]


class TestPlanner:
    """Pure planning: families, laddering, costs — no data moved."""

    def test_lrc_single_loss_plans_local_group(self):
        lrc = factory("plugin=lrc k=8 m=4 l=4 impl=bitlinear")
        n = lrc.get_chunk_count()
        rp = plan_repair(lrc, [1], [i for i in range(n) if i != 1])
        assert rp.family == "lrc_local"
        assert len(rp.helpers) == 4          # l, not k=8
        # k8m4l4 groups are 5 slots wide; slot 1 lives in group 0
        assert set(rp.helpers) <= set(range(5))
        assert rp.planes is None and rp.integrity == "row"
        assert rp.wire_fraction == 1.0

    def test_lrc_second_loss_same_group_ladders(self):
        """Broken locality: two losses in one local group can't be
        served by that group — the structural walk ladders to the
        global layer and the family says so."""
        lrc = factory("plugin=lrc k=8 m=4 l=4 impl=bitlinear")
        n = lrc.get_chunk_count()
        rp = plan_repair(lrc, [1, 2],
                         [i for i in range(n) if i not in (1, 2)])
        assert rp.family == "lrc_multi"
        assert not set(rp.helpers) <= set(range(5))   # left the group
        # still a valid plan: helpers can actually reconstruct
        assert set(lrc.minimum_to_decode([1, 2], sorted(
            set(range(n)) - {1, 2}))) <= set(rp.helpers) | {1, 2}

    def test_clay_single_loss_plans_repair_planes(self):
        clay = factory("plugin=clay k=8 m=4 impl=bitlinear")
        n = clay.get_chunk_count()
        rp = plan_repair(clay, [3], [i for i in range(n) if i != 3])
        assert rp.family == "clay_planes"
        assert len(rp.helpers) == clay.d
        assert rp.integrity == "range"
        P = clay.get_sub_chunk_count()
        assert len(rp.planes) == P // clay.q      # beta = q^(t-1)
        assert rp.wire_fraction == pytest.approx(1 / clay.q)
        sl = P * 128
        ranges = rp.ranges(sl)
        assert sum(ln for _o, ln in ranges) == rp.row_bytes(sl)
        assert rp.row_bytes(sl) == sl // clay.q

    def test_clay_multi_loss_ladders_to_full(self):
        clay = factory("plugin=clay k=8 m=4 impl=bitlinear")
        n = clay.get_chunk_count()
        rp = plan_repair(clay, [3, 4],
                         [i for i in range(n) if i not in (3, 4)])
        assert rp.family == "clay_full"
        assert rp.planes is None and rp.integrity == "row"

    def test_mds_costs_bias_helper_pick(self):
        rs = factory("plugin=tpu_rs k=4 m=2 impl=bitlinear")
        rp = plan_repair(rs, [0], [1, 2, 3, 4, 5],
                         costs={1: 10_000, 2: 1, 3: 1, 4: 1, 5: 1})
        assert rp.cost_ranked
        assert 1 not in rp.helpers           # the expensive one sat out
        assert len(rp.helpers) == 4

    def test_shec_cost_breaks_ties_structurally(self):
        """SHEC stays structural (fewest reads first) — the cost only
        picks among equally small workable sets, never an undecodable
        'cheapest k'."""
        shec = factory("plugin=shec k=4 m=3 c=2 impl=bitlinear")
        n = shec.get_chunk_count()
        avail = [i for i in range(n) if i != 0]
        base = plan_repair(shec, [0], avail)
        biased = plan_repair(shec, [0], avail,
                             costs={c: 0 for c in avail})
        assert len(biased.helpers) == len(base.helpers)
        # and the set actually decodes chunk 0
        assert set(shec.minimum_to_decode([0], sorted(
            biased.helpers))) <= set(biased.helpers)

    def test_clay_costs_never_evict_column_mates(self):
        """Clay's surviving grid-column mates are structurally required
        helpers; a hostile cost table must not push them out."""
        clay = factory("plugin=clay k=4 m=2 impl=bitlinear")
        n = clay.get_chunk_count()
        lost = 0
        avail = [i for i in range(n) if i != lost]
        y0 = clay._xy(clay._node_of_chunk(lost))[1]
        mates = {c for c in avail
                 if clay._xy(clay._node_of_chunk(c))[1] == y0}
        rp = plan_repair(clay, [lost], avail,
                         costs={c: 10_000_000 for c in mates})
        assert mates <= set(rp.helpers)

    def test_unreconstructible_raises_value_error(self):
        rs = factory("plugin=tpu_rs k=4 m=2 impl=bitlinear")
        with pytest.raises(ValueError):
            plan_repair(rs, [0, 1, 2], [3, 4])   # 2 survivors < k

    def test_coalesce_ranges(self):
        assert coalesce_ranges([(0, 4), (4, 4), (12, 4)]) \
            == ((0, 8), (12, 4))
        assert coalesce_ranges([(8, 4), (0, 4)]) == ((0, 4), (8, 4))
        assert coalesce_ranges([(0, 8), (4, 8)]) == ((0, 12),)

    def test_plan_read_lrc_degraded_gathers_local_group(self):
        lrc = factory("plugin=lrc k=4 m=2 l=3 impl=bitlinear")
        n = lrc.get_chunk_count()
        # k4m2l3 layout: group0 = slots 0..3 (0 local parity, 1 global),
        # group1 = 4..7; data positions are {2, 3, 6, 7}
        want = list(lrc.data_positions)
        lost = want[0]
        need, family = plan_read(lrc, want,
                                 [i for i in range(n) if i != lost])
        assert family == "lrc_local"
        group0 = set(range(4))
        assert need <= (set(want) | group0) - {lost}
        # and a fully-available read is a pass-through
        need2, fam2 = plan_read(lrc, want, list(range(n)))
        assert fam2 == "direct" and need2 == set(want)


def _write_corpus(be, prefix, n=6,
                  sizes=(4096, 4096, 1500, 4096, 900, 4096)):
    rng = np.random.default_rng(hash(prefix) % (2**32))
    objs = {f"{prefix}-{i}": rng.integers(0, 256, sizes[i % len(sizes)],
                                          np.uint8)
            for i in range(n)}
    be.write_objects(objs)
    return objs


def _full_decode_oracle(be, lost, names):
    """Full-k reference: decode from EVERY survivor, per object, no
    planner — the bytes the planner-driven path must reproduce."""
    out = {}
    survivors = [s for s in range(be.n) if s not in lost]
    for name in names:
        stacks = {s: be._store(s).read(shard_cid(be.pg, s), name)
                  for s in survivors}
        rec = be.coder.decode_chunks(lost, stacks)
        out[name] = {s: np.asarray(rec[s]) for s in lost}
    return out


GEOMETRIES = [
    ("plugin=tpu_rs k=4 m=2 impl=bitlinear", [1]),
    ("plugin=lrc k=4 m=2 l=3 impl=bitlinear", [2]),
    ("plugin=lrc k=4 m=2 l=3 impl=bitlinear", [2, 3]),   # broken group
    ("plugin=clay k=2 m=2 impl=bitlinear", [1]),
    ("plugin=shec k=4 m=3 c=2 impl=bitlinear", [0]),
]


class TestPlannerRecoveryBitExact:
    @pytest.mark.parametrize("host_crc", _host_crc_params())
    @pytest.mark.parametrize("profile,lost", GEOMETRIES)
    def test_rebuild_matches_full_decode_oracle(self, profile, lost,
                                                host_crc):
        cluster = ShardSet()
        n = factory(profile).get_chunk_count()
        be = ECBackend(profile, "1.0", list(range(n)), cluster,
                       chunk_size=512)
        objs = _write_corpus(be, f"bx-{lost}")
        refs = _full_decode_oracle(be, lost, sorted(objs))
        for s in lost:
            cluster.stores.pop(s)
        plan = be.plan_recovery(lost, replacement_osds={
            s: 100 + s for s in lost})
        runner = RecoveryRunner([plan], batch=4, host_crc=host_crc)
        runner.run()
        assert plan.counters["objects"] == len(objs)
        assert not plan.remaining
        for s in lost:
            st = cluster.osd(100 + s)
            cid = shard_cid("1.0", s)
            for name in sorted(objs):
                np.testing.assert_array_equal(
                    st.read(cid, name), refs[name][s],
                    err_msg=f"{profile} {name} slot {s}")
        # the PG serves client reads again
        got = be.read_objects(sorted(objs))
        for name, data in objs.items():
            np.testing.assert_array_equal(got[name], data,
                                          err_msg=name)

    def test_planner_moves_fewer_bytes_than_full_k(self):
        """The point of the subsystem: LRC local repair and Clay range
        reads pull strictly fewer helper bytes than a full-k plan
        would for the same rebuild."""
        for profile, expect_frac in [
                ("plugin=lrc k=8 m=4 l=4 impl=bitlinear", 0.55),
                ("plugin=clay k=2 m=2 impl=bitlinear", 0.80)]:
            cluster = ShardSet()
            coder = factory(profile)
            n = coder.get_chunk_count()
            k = coder.get_data_chunk_count()
            be = ECBackend(profile, "1.0", list(range(n)), cluster,
                           chunk_size=512)
            objs = _write_corpus(be, "wb", n=4, sizes=(4096,))
            cluster.stores.pop(1)
            plan = be.plan_recovery([1], replacement_osds={1: 50})
            runner = RecoveryRunner([plan], batch=4)
            runner.run()
            rebuilt = plan.counters["bytes"]
            wire = runner.stats["helper_bytes_on_wire"]
            assert wire / (rebuilt * k) <= expect_frac, profile
            got = be.read_objects(sorted(objs))
            for name, data in objs.items():
                np.testing.assert_array_equal(got[name], data)

    def test_recover_shards_helper_costs_bias(self):
        """recover_shards(helper_costs=...) routes the costs into the
        planner: an expensively-priced survivor sits out when k others
        are available."""
        cluster = ShardSet()
        be = ECBackend("plugin=tpu_rs k=4 m=2 impl=bitlinear", "1.0",
                       list(range(6)), cluster, chunk_size=512)
        _write_corpus(be, "hc", n=3, sizes=(2048,))
        cluster.stores.pop(1)
        plan = be.plan_recovery([1], replacement_osds={1: 60},
                                helper_costs={0: 0, 2: 999_999, 3: 0,
                                              4: 0, 5: 0})
        RecoveryRunner([plan]).run()
        assert 2 not in plan.helper
        assert plan.repair.cost_ranked


class TestRangeIntegrity:
    """Sub-chunk reads break the whole-row fold — rot detection must
    survive the move to the source + range CRCs."""

    @pytest.mark.parametrize("host_crc", _host_crc_params())
    def test_rot_in_shipped_plane_detected_and_decoded_around(
            self, host_crc):
        cluster = ShardSet()
        be = ECBackend("plugin=clay k=2 m=2 impl=bitlinear", "1.0",
                       list(range(4)), cluster, chunk_size=512)
        objs = _write_corpus(be, "rot", n=4, sizes=(4096,))
        refs = _full_decode_oracle(be, [1], sorted(objs))
        # corrupt a byte INSIDE a repair plane of helper slot 2 —
        # the shipped ranges carry the rot, and the range CRC matches
        # the rotten bytes as shipped (the fold can't see it): only
        # the source-side full-row hinfo verify catches it
        rp = plan_repair(be.coder, [1], [0, 2, 3])
        sl = be._shard_len(4096)
        off = rp.ranges(sl)[0][0] + 3
        cluster.osd(2).queue_transaction(
            Transaction().write(shard_cid("1.0", 2), "rot-0", off,
                                b"\xEE"))
        cluster.stores.pop(1)
        plan = be.plan_recovery([1], replacement_osds={1: 70})
        assert plan.range_planes is not None      # range mode active
        runner = RecoveryRunner([plan], batch=4, host_crc=host_crc)
        runner.run()
        assert plan.counters["hinfo_failures"] >= 1
        st = cluster.osd(70)
        cid = shard_cid("1.0", 1)
        for name in sorted(objs):
            np.testing.assert_array_equal(st.read(cid, name),
                                          refs[name][1], err_msg=name)

    def test_rot_outside_shipped_planes_still_flagged(self):
        """The source verifies the FULL shard, so rot in bytes the
        plan never ships is still caught (a later full-row read would
        have tripped over it) and the rebuild decodes around it."""
        cluster = ShardSet()
        be = ECBackend("plugin=clay k=2 m=2 impl=bitlinear", "1.0",
                       list(range(4)), cluster, chunk_size=512)
        objs = _write_corpus(be, "rq", n=3, sizes=(4096,))
        refs = _full_decode_oracle(be, [1], sorted(objs))
        rp = plan_repair(be.coder, [1], [0, 2, 3])
        sl = be._shard_len(4096)
        shipped = rp.ranges(sl)
        outside = next(o for o in range(sl)
                       if not any(lo <= o < lo + ln
                                  for lo, ln in shipped))
        cluster.osd(3).queue_transaction(
            Transaction().write(shard_cid("1.0", 3), "rq-1", outside,
                                b"\x5A"))
        cluster.stores.pop(1)
        plan = be.plan_recovery([1], replacement_osds={1: 71})
        RecoveryRunner([plan], batch=4).run()
        assert plan.counters["hinfo_failures"] >= 1
        st = cluster.osd(71)
        for name in sorted(objs):
            np.testing.assert_array_equal(
                st.read(shard_cid("1.0", 1), name), refs[name][1],
                err_msg=name)

    def test_no_verify_skips_source_pass(self):
        """verify_hinfo=False must not pay the source-side full-row
        CRC pass (and still rebuild correctly on clean data)."""
        cluster = ShardSet()
        be = ECBackend("plugin=clay k=2 m=2 impl=bitlinear", "1.0",
                       list(range(4)), cluster, chunk_size=512)
        objs = _write_corpus(be, "nv", n=3, sizes=(4096,))
        refs = _full_decode_oracle(be, [1], sorted(objs))
        cluster.stores.pop(1)
        plan = be.plan_recovery([1], replacement_osds={1: 72},
                                verify_hinfo=False)
        RecoveryRunner([plan], batch=4).run()
        assert plan.counters["hinfo_failures"] == 0
        st = cluster.osd(72)
        for name in sorted(objs):
            np.testing.assert_array_equal(
                st.read(shard_cid("1.0", 1), name), refs[name][1])


class TestDegradedLocalRead:
    def test_lrc_degraded_read_touches_only_local_group(self):
        """ROADMAP item 3 follow-up: a degraded read with one lost
        LRC data shard gathers direct data + ONE local group — the
        other group's parities are never touched."""
        cluster = ShardSet()
        be = ECBackend("plugin=lrc k=4 m=2 l=3 impl=bitlinear", "1.0",
                       list(range(8)), cluster, chunk_size=512)
        objs = _write_corpus(be, "dg", n=4, sizes=(4096,))
        lost = be.data_slots[0]           # a data position in group 0
        group0 = set(range(4))
        assert lost in group0
        before = be.perf.dump()["planner_local_plans"]
        touched: set[int] = set()
        for s in range(be.n):
            st = be._store(s)
            orig = st.read

            def spy(cid, oid, *a, _orig=orig, _s=s, **kw):
                touched.add(_s)
                return _orig(cid, oid, *a, **kw)
            st.read = spy
        got = be.read_objects(sorted(objs), dead_osds={lost},
                              repair=False)
        for name, data in objs.items():
            np.testing.assert_array_equal(got[name], data,
                                          err_msg=name)
        allowed = (set(be.data_slots) | group0) - {lost}
        assert touched <= allowed, touched
        assert be.perf.dump()["planner_local_plans"] > before


class TestWireRangeRecovery:
    """Tier-1 representative of the wire path: a real clay cluster
    rebuilds a killed OSD over readv_ranges frames (sub-chunk pulls),
    bit-exact, with the planner counters attributing the plan.

    Deadlines scale with the host's observed load (the r11
    test_standalone deflake rule): tuned on an idle box, these cells
    passed alone but flaked in-suite at r15 when the 1-core host was
    oversubscribed — the load factor stretches the DEADLINE without
    loosening the assertion. The factor is RE-SAMPLED at each wait
    (r19 deflake): one reading taken while the suite was momentarily
    idle under-scaled the long recovery wait minutes later, which is
    exactly when the box is busiest."""

    def test_clay_wire_rebuild_over_range_frames(self):
        from ceph_tpu.chaos import load_factor
        from ceph_tpu.osd.standalone import StandaloneCluster
        # 5 OSDs for a size-4 pool: the killed slot needs a spare OSD
        # to re-home onto, or the PG can never go clean
        c = StandaloneCluster(
            n_osds=5, pg_num=2, op_timeout=5.0 * load_factor(),
            profile="plugin=clay k=2 m=2 impl=bitlinear",
            chunk_size=512)
        try:
            c.wait_for_clean(timeout=30 * load_factor())
            cl = c.client()
            rng = np.random.default_rng(7)
            objs = {f"wr-{i}": rng.integers(0, 256, 2048,
                                            np.uint8).tobytes()
                    for i in range(10)}
            cl.write(objs)
            primaries = {cl.osdmap.pg_to_up_acting_osds(1, ps)[2][0]
                         for ps in range(2)}
            victim = next(o for o in c.osd_ids()
                          if o not in primaries)
            c.kill_osd(victim)
            c.wait_for_down(victim, timeout=30 * load_factor())
            c.wait_for_clean(timeout=90 * load_factor())
            cl2 = c.client("client.admin2")
            for name, want in objs.items():
                assert cl2.read(name) == want, name
            plans = wire = 0
            for d in c.osds.values():
                if d._stop.is_set():
                    continue
                dump = d.ec_perf.dump()
                plans += dump["planner_subchunk_plans"]
                wire += dump["recover_wire_bytes"]
            assert plans >= 1        # the rebuild went through planes
            assert wire > 0
        finally:
            c.shutdown()
