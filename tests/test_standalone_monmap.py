"""Monitor membership changes over the wire tier (refs:
src/mon/MonMap.h, MonmapMonitor::prepare_join, `ceph mon add/remove`;
quorum reconfiguration by committing the new membership through the
old quorum)."""

import numpy as np
import pytest

from ceph_tpu.osd.standalone import StandaloneCluster


@pytest.fixture
def cluster():
    c = StandaloneCluster(n_osds=3, pg_num=2, op_timeout=3.0)
    try:
        c.wait_for_clean(timeout=20)
        yield c
    finally:
        c.shutdown()


def corpus(seed, n=6):
    rng = np.random.default_rng(seed)
    return {f"mm-{seed}-{i}":
            rng.integers(0, 256, 200, np.uint8).tobytes()
            for i in range(n)}


class TestMonMembership:
    def test_grow_to_five_survives_two_mon_deaths(self, cluster):
        """3 monitors tolerate one death; after growing to 5 the
        cluster commits through two deaths — the membership change
        really moved the quorum math."""
        r3 = cluster.add_mon()
        r4 = cluster.add_mon()
        assert (r3, r4) == (3, 4)
        live_map = next(m.osdmap for m in cluster.mons
                        if m.osdmap is not None)
        assert live_map.mon_members == [0, 1, 2, 3, 4]
        cl = cluster.client()
        objs = corpus(1)
        cl.write(objs)
        cluster.kill_mon(1)
        cluster.kill_mon(2)
        # 3 of 5 members alive: mksnap must still reach quorum commit
        sid = cl.snap_create("after-two-deaths", timeout=20.0)
        assert sid >= 1
        name = next(iter(objs))
        assert cl.read(name) == objs[name]

    def test_shrink_back_to_three(self, cluster):
        cluster.add_mon()
        cluster.add_mon()
        cluster.remove_mon(4)
        cluster.remove_mon(3)
        live_map = next(m.osdmap for m in cluster.mons[:3]
                        if m.osdmap is not None)
        assert live_map.mon_members == [0, 1, 2]
        cl = cluster.client()
        cl.write(corpus(2))
        assert cl.snap_create("post-shrink", timeout=20.0) >= 1

    def test_removed_leader_stops_leading(self, cluster):
        """Removing rank 0 (the leader) moves leadership to rank 1 and
        commits keep working; the removed monitor no longer counts
        itself a member."""
        cl = cluster.client()
        cl.write(corpus(3))
        cluster.remove_mon(0)
        cluster._wait(
            lambda: any(not m._stop.is_set() and m.is_leader()
                        for m in cluster.mons[1:3]), 15,
            "new leader among ranks 1-2")
        assert not cluster.mons[0].is_leader()
        assert cl.snap_create("post-leader-removal",
                              timeout=20.0) >= 1

    def test_membership_change_commits_through_partition_majority(
            self, cluster):
        """`mon add` while a member is partitioned away: the change
        commits through the majority side; the isolated monitor folds
        it on heal (quorum intersection)."""
        c = cluster
        c.partition({"mon.2"}, {"mon.0", "mon.1"})
        rank = c.add_mon(timeout=25)    # via majority {0, 1}
        assert rank == 3
        # partition() blocks only endpoints existing when installed:
        # re-apply with the new monitor in the majority group so
        # mon.2 stays genuinely isolated from EVERYONE
        c.partition({"mon.2"}, {"mon.0", "mon.1", "mon.3"})
        maj_map = next(m.osdmap for m in c.mons[:2]
                       if m.osdmap is not None)
        assert rank in maj_map.mon_members
        cl = c.client()
        cl.write({"post-join": b"committed through 0/1/3"})
        assert cl.read("post-join") == b"committed through 0/1/3"
        c.heal_partition()
        c._wait(lambda: c.mons[2].osdmap is not None
                and rank in c.mons[2].osdmap.mon_members, 25,
                "isolated monitor folds the membership commit")

    def test_new_mon_serves_auth_and_maps(self):
        """A joined monitor is a full citizen: it syncs the map and
        (cephx) serves tickets."""
        c = StandaloneCluster(n_osds=3, pg_num=2, op_timeout=3.0,
                              cephx=True)
        try:
            c.wait_for_clean(timeout=20)
            rank = c.add_mon()
            fresh = c.mons[rank]
            assert fresh.osdmap is not None
            assert fresh.auth_svc is not None
            # kill every OTHER monitor: auth + commits must ride the
            # new one... (3 of 5 needed; kill only rank 1 to stay
            # quorate: members [0,1,2,3], majority 3, alive {0,2,3})
            c.kill_mon(1)
            cl = c.client()
            objs = corpus(4)
            cl.write(objs)
            for nm, want in objs.items():
                assert cl.read(nm) == want
        finally:
            c.shutdown()
