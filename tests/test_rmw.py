"""RMW partial-stripe write tests (ref: ECCommon::RMWPipeline::start_rmw,
ECTransaction::generate_transactions — arbitrary (offset, len) overwrites
read the touched stripes' pre-image, re-encode, and sub-write shards).

The property test mirrors the reference's thrash-under-io pattern
(qa/tasks/ceph_manager.py Thrasher): random full/partial writes
interleaved with OSD kills and recoveries, every read byte-exact vs a
host-side shadow copy.
"""

import numpy as np
import pytest

from ceph_tpu.osd.ecbackend import ECBackend, ShardSet, shard_cid


def make_backend(profile="plugin=tpu_rs k=4 m=2 impl=bitlinear",
                 n_osds=6, chunk_size=256):
    cluster = ShardSet()
    be = ECBackend(profile, "1.0", list(range(n_osds)), cluster,
                   chunk_size=chunk_size)
    return be, cluster


class TestWriteAt:
    def test_overwrite_within_one_stripe(self):
        be, _ = make_backend()
        rng = np.random.default_rng(0)
        base = rng.integers(0, 256, size=3000, dtype=np.uint8)
        be.write_objects({"o": base})
        patch = rng.integers(0, 256, size=100, dtype=np.uint8)
        be.write_at("o", 50, patch)
        want = base.copy()
        want[50:150] = patch
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []

    def test_overwrite_spanning_stripes(self):
        be, _ = make_backend()
        rng = np.random.default_rng(1)
        sw = be.sinfo.stripe_width
        base = rng.integers(0, 256, size=sw * 3 + 17, dtype=np.uint8)
        be.write_objects({"o": base})
        patch = rng.integers(0, 256, size=sw + 33, dtype=np.uint8)
        off = sw - 5
        be.write_at("o", off, patch)
        want = base.copy()
        want[off:off + len(patch)] = patch
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []

    def test_extending_write(self):
        be, _ = make_backend()
        rng = np.random.default_rng(2)
        base = rng.integers(0, 256, size=500, dtype=np.uint8)
        be.write_objects({"o": base})
        tail = rng.integers(0, 256, size=800, dtype=np.uint8)
        be.write_at("o", 450, tail)
        want = np.concatenate([base[:450], tail])
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []

    def test_write_past_end_zero_gap(self):
        be, _ = make_backend()
        rng = np.random.default_rng(3)
        base = rng.integers(0, 256, size=100, dtype=np.uint8)
        be.write_objects({"o": base})
        sw = be.sinfo.stripe_width
        patch = rng.integers(0, 256, size=64, dtype=np.uint8)
        off = sw * 2 + 7  # leaves a hole of untouched stripes
        be.write_at("o", off, patch)
        want = np.zeros(off + 64, dtype=np.uint8)
        want[:100] = base
        want[off:off + 64] = patch
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []

    def test_write_at_creates_object(self):
        be, _ = make_backend()
        rng = np.random.default_rng(4)
        patch = rng.integers(0, 256, size=300, dtype=np.uint8)
        be.write_at("new", 40, patch)
        want = np.zeros(340, dtype=np.uint8)
        want[40:] = patch
        np.testing.assert_array_equal(be.read_object("new"), want)

    def test_empty_write_noop_and_creation(self):
        be, _ = make_backend()
        be.write_at("e", 0, b"")
        assert be.read_object("e").size == 0
        rng = np.random.default_rng(5)
        base = rng.integers(0, 256, size=100, dtype=np.uint8)
        be.write_objects({"o": base})
        be.write_at("o", 10, b"")
        np.testing.assert_array_equal(be.read_object("o"), base)

    def test_batched_write_ranges_multiple_objects(self):
        be, _ = make_backend()
        rng = np.random.default_rng(6)
        objs = {f"o{i}": rng.integers(0, 256, size=2048, dtype=np.uint8)
                for i in range(5)}
        be.write_objects(dict(objs))
        ops = []
        for i, name in enumerate(objs):
            patch = rng.integers(0, 256, size=64, dtype=np.uint8)
            ops.append((name, 100 + 17 * i, patch))
            objs[name][100 + 17 * i:100 + 17 * i + 64] = patch
        be.write_ranges(ops)
        got = be.read_objects(list(objs))
        for name, want in objs.items():
            np.testing.assert_array_equal(got[name], want, err_msg=name)
        assert be.deep_scrub()["inconsistent"] == []

    def test_multiple_ranges_same_object_merge(self):
        be, _ = make_backend()
        rng = np.random.default_rng(7)
        base = rng.integers(0, 256, size=4000, dtype=np.uint8)
        be.write_objects({"o": base})
        a = rng.integers(0, 256, size=50, dtype=np.uint8)
        b = rng.integers(0, 256, size=60, dtype=np.uint8)
        be.write_ranges([("o", 10, a), ("o", 3000, b)])
        want = base.copy()
        want[10:60] = a
        want[3000:3060] = b
        np.testing.assert_array_equal(be.read_object("o"), want)


class TestDegradedRMW:
    def test_rmw_with_down_data_shard(self):
        """Write with a data shard's OSD down: pre-image reconstructed
        from survivors, parity stays consistent, recovery rebuilds the
        down shard with the NEW bytes."""
        be, cluster = make_backend()
        rng = np.random.default_rng(10)
        base = rng.integers(0, 256, size=3000, dtype=np.uint8)
        be.write_objects({"o": base})
        dead_osd = be.acting[1]  # data shard slot 1
        cluster.stores.pop(dead_osd)
        patch = rng.integers(0, 256, size=500, dtype=np.uint8)
        be.write_at("o", 200, patch, dead_osds={dead_osd})
        want = base.copy()
        want[200:700] = patch
        np.testing.assert_array_equal(
            be.read_object("o", dead_osds={dead_osd}), want)
        # recovery rebuilds slot 1 from the new stripe content
        be.recover_shards([1], replacement_osds={1: 77})
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []

    def test_rmw_with_down_parity_shard(self):
        be, cluster = make_backend()
        rng = np.random.default_rng(11)
        base = rng.integers(0, 256, size=3000, dtype=np.uint8)
        be.write_objects({"o": base})
        dead_osd = be.acting[be.k]  # first parity slot
        cluster.stores.pop(dead_osd)
        patch = rng.integers(0, 256, size=100, dtype=np.uint8)
        be.write_at("o", 700, patch, dead_osds={dead_osd})
        want = base.copy()
        want[700:800] = patch
        be.recover_shards([be.k], replacement_osds={be.k: 78})
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []


class TestClayRMW:
    def test_clay_falls_back_to_whole_object(self):
        be, _ = make_backend(profile="plugin=clay k=4 m=2 d=5 impl=ref",
                             chunk_size=None)
        rng = np.random.default_rng(12)
        base = rng.integers(0, 256, size=5000, dtype=np.uint8)
        be.write_objects({"o": base})
        patch = rng.integers(0, 256, size=70, dtype=np.uint8)
        be.write_at("o", 123, patch)
        want = base.copy()
        want[123:193] = patch
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []


class TestRMWProperty:
    @pytest.mark.parametrize("profile", [
        "plugin=tpu_rs k=4 m=2 impl=bitlinear",
        "plugin=tpu_rs k=3 m=3 technique=cauchy_good impl=logexp",
    ])
    def test_thrash_partial_writes_and_kills(self, profile):
        """Random full/partial writes interleaved with OSD kills and
        recoveries; every read byte-exact vs the host shadow."""
        rng = np.random.default_rng(99)
        be, cluster = make_backend(profile=profile, n_osds=6, chunk_size=256)
        shadow: dict[str, np.ndarray] = {}
        dead: dict[int, int] = {}  # slot -> dead osd id
        next_osd = 100
        for step in range(60):
            op = rng.choice(["full", "partial", "kill", "recover", "verify"],
                            p=[0.2, 0.45, 0.1, 0.1, 0.15])
            dead_osds = set(dead.values())
            if op == "full":
                name = f"obj{rng.integers(0, 8)}"
                size = int(rng.integers(0, 3000))
                data = rng.integers(0, 256, size=size, dtype=np.uint8)
                # full-object rewrite must work degraded too: route via
                # write_ranges when shards are down (write_objects is the
                # clean-path batch API)
                if dead_osds:
                    be.write_ranges([(name, 0, data)], dead_osds=dead_osds)
                    if name in shadow and len(shadow[name]) > size:
                        # emulate truncate-to-size of a full rewrite:
                        # write_ranges alone extends, so pad the shadow
                        grown = shadow[name].copy()
                        grown[:size] = data
                        shadow[name] = grown
                    else:
                        shadow[name] = data
                else:
                    be.write_objects({name: data})
                    shadow[name] = data
            elif op == "partial":
                name = f"obj{rng.integers(0, 8)}"
                old = shadow.get(name, np.zeros(0, dtype=np.uint8))
                off = int(rng.integers(0, 2500))
                ln = int(rng.integers(1, 600))
                patch = rng.integers(0, 256, size=ln, dtype=np.uint8)
                be.write_at(name, off, patch, dead_osds=dead_osds)
                new_len = max(len(old), off + ln)
                grown = np.zeros(new_len, dtype=np.uint8)
                grown[:len(old)] = old
                grown[off:off + ln] = patch
                shadow[name] = grown
            elif op == "kill" and len(dead) < be.m:
                alive = [s for s in range(be.n) if s not in dead]
                slot = int(rng.choice(alive))
                dead[slot] = be.acting[slot]
                cluster.stores.pop(be.acting[slot], None)
            elif op == "recover" and dead:
                slots = sorted(dead)
                be.recover_shards(slots, replacement_osds={
                    s: next_osd + i for i, s in enumerate(slots)})
                next_osd += len(slots)
                dead.clear()
            else:  # verify
                if shadow:
                    got = be.read_objects(list(shadow),
                                          dead_osds=set(dead.values()))
                    for name, want in shadow.items():
                        np.testing.assert_array_equal(
                            got[name], want, err_msg=f"step {step} {name}")
        # final: recover everything and verify clean
        if dead:
            slots = sorted(dead)
            be.recover_shards(slots, replacement_osds={
                s: next_osd + i for i, s in enumerate(slots)})
        got = be.read_objects(list(shadow))
        for name, want in shadow.items():
            np.testing.assert_array_equal(got[name], want, err_msg=name)
        assert be.deep_scrub()["inconsistent"] == []


class TestClayDegradedExtendingRMW:
    def test_clay_degraded_extend_preserves_old_bytes(self):
        """Review regression: clay sub-chunk geometry depends on chunk
        length, so the degraded pre-image must be decoded at the OLD
        shard length, not the zero-extended new one."""
        be, cluster = make_backend(profile="plugin=clay k=4 m=2 d=5 impl=ref",
                                   chunk_size=None)
        rng = np.random.default_rng(21)
        sw = be.sinfo.stripe_width
        base = rng.integers(0, 256, size=sw, dtype=np.uint8)
        be.write_objects({"o": base})
        dead_osd = be.acting[1]
        cluster.stores.pop(dead_osd)
        patch = rng.integers(0, 256, size=300, dtype=np.uint8)
        be.write_at("o", sw, patch, dead_osds={dead_osd})  # extends
        want = np.concatenate([base, patch])
        np.testing.assert_array_equal(
            be.read_object("o", dead_osds={dead_osd}), want)
        # the destroyed OSD id must NOT have been resurrected
        assert dead_osd not in cluster.stores
        be.recover_shards([1], replacement_osds={1: 55})
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []
