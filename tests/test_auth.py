"""cephx-shaped ticket auth (refs: src/auth/cephx/CephxProtocol.cc
ticket flow, CephxKeyServer rotating secrets, src/mon/AuthMonitor.cc,
MonCap/OSDCap grammar)."""

import pytest

from ceph_tpu.auth import (AuthError, AuthService, Caps, ClientAuth,
                           KeyServer, NeedChallenge, ServiceVerifier,
                           local_authorize)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def setup_realm(ttl=3600.0):
    clock = FakeClock()
    ks = KeyServer(ttl=ttl, now_fn=clock)
    auth = AuthService(ks)
    secret = ks.create_entity(
        "client.admin",
        caps={"mon": "allow *", "osd": "allow rw"})
    client = ClientAuth(auth, "client.admin", secret, now_fn=clock)
    osd = ServiceVerifier("osd", ks.export_rotating("osd"),
                          now_fn=clock)
    return clock, ks, auth, client, osd


class TestHandshake:
    def test_full_flow_and_mutual_auth(self):
        clock, ks, auth, client, osd = setup_realm()
        client.login()
        client.fetch_tickets(["osd"])
        az = client.authorizer_for("osd")
        with pytest.raises(NeedChallenge) as nc:
            osd.verify(az, peer="c1")        # anti-replay round first
        az = client.authorizer_for(
            "osd", server_challenge=nc.value.challenge)
        got = osd.verify(az, peer="c1")
        assert got["entity"] == "client.admin"
        assert got["caps"]["osd"].allows("w")
        assert client.verify_reply("osd", az, got["reply_mac"])

    def test_wrong_entity_secret_rejected(self):
        clock, ks, auth, client, osd = setup_realm()
        client.secret = b"\x00" * 32
        with pytest.raises(AuthError, match="bad proof"):
            client.login()

    def test_unknown_entity_rejected(self):
        clock, ks, auth, client, osd = setup_realm()
        with pytest.raises(AuthError, match="unknown entity"):
            auth.hello("client.nobody", b"x")

    def test_challenge_single_use(self):
        """A captured proof cannot be replayed: the server challenge
        is consumed by the first authenticate."""
        clock, ks, auth, client, osd = setup_realm()
        import os
        from ceph_tpu.auth.cephx import _hmac
        cc = os.urandom(16)
        sc = auth.hello("client.admin", cc)
        proof = _hmac(client.secret, sc, cc)
        auth.authenticate("client.admin", cc, proof)
        with pytest.raises(AuthError, match="replay"):
            auth.authenticate("client.admin", cc, proof)

    def test_tampered_ticket_rejected(self):
        clock, ks, auth, client, osd = setup_realm()
        client.fetch_tickets(["osd"])
        az = client.authorizer_for("osd")
        blob = bytearray(bytes.fromhex(az["ticket"]["blob"]))
        blob[20] ^= 0xFF
        az["ticket"]["blob"] = bytes(blob).hex()
        with pytest.raises(AuthError, match="tampered|authentication"):
            osd.verify(az, peer="c1")

    def test_forged_mac_rejected(self):
        clock, ks, auth, client, osd = setup_realm()
        az = client.authorizer_for("osd")
        with pytest.raises(NeedChallenge) as nc:
            osd.verify(az, peer="c1")
        az = client.authorizer_for(
            "osd", server_challenge=nc.value.challenge)
        az["mac"] = "00" * 32
        with pytest.raises(AuthError, match="MAC"):
            osd.verify(az, peer="c1")

    def test_captured_authorizer_replay_rejected(self):
        """The CVE-2018-1128 scenario: a frame-capturing attacker
        replays a once-valid authorizer. The challenge round makes
        every accepted authorizer single-use and challenge-bound, so
        the replay is refused from any peer — including the one the
        original was accepted on."""
        clock, ks, auth, client, osd = setup_realm()
        az = client.authorizer_for("osd")
        with pytest.raises(NeedChallenge) as nc:
            osd.verify(az, peer="victim")
        az = client.authorizer_for(
            "osd", server_challenge=nc.value.challenge)
        assert osd.verify(az, peer="victim")["entity"] == "client.admin"
        # same frame, same peer: the challenge was consumed
        with pytest.raises(NeedChallenge):
            osd.verify(az, peer="victim")
        # same frame, attacker's connection: different outstanding
        # challenge, MAC can't match it
        with pytest.raises(NeedChallenge):
            osd.verify(az, peer="attacker")
        with pytest.raises((AuthError, NeedChallenge)):
            osd.verify(az, peer="attacker")

    def test_osd_never_sees_entity_secret(self):
        """The ticket blob carries a per-session key, not the entity
        secret — compromise of one OSD leaks no long-term keys."""
        clock, ks, auth, client, osd = setup_realm()
        az = client.authorizer_for("osd")
        got = local_authorize(client, osd, "osd")
        assert got["session_key"] != client.secret
        assert client.secret.hex() not in az["ticket"]["blob"]


class TestExpiryAndRotation:
    def test_expired_ticket_rejected_then_refreshed(self):
        clock, ks, auth, client, osd = setup_realm(ttl=100.0)
        az = client.authorizer_for("osd")
        local_authorize(client, osd, "osd")
        clock.t += 200.0             # past ticket ttl
        with pytest.raises(AuthError, match="expired"):
            osd.verify(az, peer="x")
        # authorizer_for auto-refreshes (client re-logs-in under the
        # still-valid entity secret); the KeyServer auto-rotated past
        # the aged secret, so the daemon refreshes its window too (the
        # wire tier does this on unknown-sid automatically)
        client.session_key = None    # old session expired too
        osd.refresh(ks.export_rotating("osd"))
        got = local_authorize(client, osd, "osd")
        assert got["entity"] == "client.admin"

    def test_rotation_window(self):
        """Tickets under the previous rotating secret still verify;
        after the secret rotates out, they're refused."""
        clock, ks, auth, client, osd = setup_realm()
        client.fetch_tickets(["osd"])   # ticket under the first sid
        ks.rotate("osd")
        ks.rotate("osd")
        osd.refresh(ks.export_rotating("osd"))
        got = local_authorize(client, osd, "osd")   # still in window
        assert got["entity"] == "client.admin"
        ks.rotate("osd")             # now rotated out (keep = 3)
        osd.refresh(ks.export_rotating("osd"))
        with pytest.raises(AuthError, match="rotated out"):
            local_authorize(client, osd, "osd")
        # daemon told the client to refresh: fetch anew and retry
        client.fetch_tickets(["osd"])
        got = local_authorize(client, osd, "osd")
        assert got["entity"] == "client.admin"

    def test_expired_auth_ticket_triggers_relogin(self):
        """A long-lived client whose AUTH ticket aged out re-logins
        under its entity secret transparently — fetch_tickets must not
        surface 'auth ticket expired' (the soak-run path)."""
        clock, ks, auth, client, osd = setup_realm(ttl=100.0)
        client.login()
        clock.t += 200.0             # auth ticket now expired
        client.fetch_tickets(["osd"])    # must re-login internally
        osd.refresh(ks.export_rotating("osd"))   # window moved with time
        got = local_authorize(client, osd, "osd")
        assert got["entity"] == "client.admin"

    def test_new_tickets_use_current_secret(self):
        clock, ks, auth, client, osd = setup_realm()
        sid0, _ = ks.current_secret("osd")
        ks.rotate("osd")
        client.fetch_tickets(["osd"])
        az = client.authorizer_for("osd")
        assert az["ticket"]["secret_id"] != sid0
        osd.refresh(ks.export_rotating("osd"))
        got = local_authorize(client, osd, "osd")
        assert got["entity"] == "client.admin"


class TestCaps:
    def test_basic_grammar(self):
        c = Caps("allow rw pool=rbd, allow r")
        assert c.allows("r")
        assert c.allows("w", pool="rbd")
        assert not c.allows("w", pool="cephfs")
        assert not c.allows("x")

    def test_star(self):
        c = Caps("allow *")
        assert c.allows("r") and c.allows("w") and c.allows("x")

    def test_empty_denies_all(self):
        c = Caps("")
        assert not c.allows("r")

    def test_bad_grammar(self):
        with pytest.raises(AuthError):
            Caps("deny r")
        with pytest.raises(AuthError):
            Caps("allow q")

    def test_caps_ride_the_ticket(self):
        clock = FakeClock()
        ks = KeyServer(now_fn=clock)
        auth = AuthService(ks)
        s = ks.create_entity("client.ro",
                             caps={"osd": "allow r pool=default"})
        cl = ClientAuth(auth, "client.ro", s, now_fn=clock)
        osd = ServiceVerifier("osd", ks.export_rotating("osd"),
                              now_fn=clock)
        got = local_authorize(cl, osd, "osd")
        assert got["caps"]["osd"].allows("r", pool="default")
        assert not got["caps"]["osd"].allows("w", pool="default")
        assert not got["caps"]["osd"].allows("r", pool="other")


class TestChallengeFlood:
    """Pending-challenge eviction must be per-entity + by age: an
    unauthenticated peer spamming hello() for one known entity name
    must not evict another entity's in-flight login (r4 advisor
    finding; ref: CephxServiceHandler server challenge lifetime)."""

    def test_spam_does_not_evict_other_entity(self):
        import os as _os

        from ceph_tpu.auth.cephx import _hmac
        clock, ks, auth, client, osd = setup_realm()
        bob_secret = ks.create_entity("client.bob",
                                      caps={"mon": "allow r"})
        # bob's login is in flight: hello done, authenticate pending
        bob_cc = _os.urandom(16)
        bob_sc = auth.hello("client.bob", bob_cc)
        # attacker spams hello() with a known entity name far past
        # every cap — only the attacker entity's challenges may churn
        for _ in range(4 * AuthService.MAX_PENDING):
            auth.hello("client.admin", _os.urandom(16))
        got = auth.authenticate("client.bob", bob_cc,
                                _hmac(bob_secret, bob_sc, bob_cc))
        assert "ticket" in got

    def test_per_entity_cap(self):
        clock, ks, auth, client, osd = setup_realm()
        for _ in range(3 * AuthService.MAX_PENDING_PER_ENTITY):
            auth.hello("client.admin", b"x" * 16)
        mine = [k for k in auth._pending if k[0] == "client.admin"]
        assert len(mine) <= AuthService.MAX_PENDING_PER_ENTITY

    def test_challenge_age_expiry(self):
        import os as _os

        from ceph_tpu.auth.cephx import _hmac
        clock, ks, auth, client, osd = setup_realm()
        secret = ks.entities["client.admin"]["secret"]
        cc = _os.urandom(16)
        sc = auth.hello("client.admin", cc)
        clock.t += AuthService.PENDING_TTL + 1
        with pytest.raises(AuthError, match="expired|replay"):
            auth.authenticate("client.admin", cc,
                              _hmac(secret, sc, cc))

    def test_global_pressure_evicts_heaviest_entity(self):
        """With the global table full of attacker entries across many
        known entity names, a fresh entity's login must still get a
        challenge (eviction targets the heaviest entity, never
        hard-rejects uninvolved logins)."""
        import os as _os

        from ceph_tpu.auth.cephx import _hmac
        clock, ks, auth, client, osd = setup_realm()
        names = [f"osd.{i}" for i in range(64)]
        for n in names:
            ks.create_entity(n, caps={"osd": "allow *"})
        for _ in range(8):
            for n in names:
                auth.hello(n, _os.urandom(16))
        assert len(auth._pending) >= AuthService.MAX_PENDING
        fresh_secret = ks.create_entity("client.fresh",
                                        caps={"mon": "allow r"})
        cc = _os.urandom(16)
        sc = auth.hello("client.fresh", cc)     # must not raise
        got = auth.authenticate("client.fresh", cc,
                                _hmac(fresh_secret, sc, cc))
        assert "ticket" in got
