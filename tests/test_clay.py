"""Clay plugin tests — mirrors the reference's TestErasureCodeClay.cc
pattern (encode random buffers, erase every <=m subset, decode,
byte-compare) plus the MSR repair-bandwidth properties."""

import numpy as np
import pytest

from ceph_tpu.ec.clay import Clay
from ceph_tpu.ec.registry import factory
from itertools import combinations


def make(k, m, d=None, **extra):
    prof = {"k": str(k), "m": str(m), "impl": "ref"}
    if d is not None:
        prof["d"] = str(d)
    prof.update({key: str(v) for key, v in extra.items()})
    return Clay(prof)


def rand_chunks(coder, B=2, seed=0):
    rng = np.random.default_rng(seed)
    L = coder.get_chunk_size(coder.k * coder.sub_chunk_count * 4)
    data = rng.integers(0, 256, size=(B, coder.k, L), dtype=np.uint8)
    parity = coder.encode_chunks(data)
    full = {i: data[:, i, :] for i in range(coder.k)}
    full.update({coder.k + j: parity[:, j, :] for j in range(coder.m)})
    return full, L


def test_registry():
    c = factory("plugin=clay k=4 m=2 impl=ref")
    assert isinstance(c, Clay)
    assert c.d == 5 and c.q == 2 and c.t == 3
    assert c.get_sub_chunk_count() == 8


def test_geometry_default_d():
    c = make(4, 2)
    assert (c.q, c.t, c.nu) == (2, 3, 0)
    c = make(8, 4, 11)
    assert (c.q, c.t, c.nu) == (4, 3, 0)
    c = make(5, 4, 8)  # k+m=9, q=4 -> t=3, nu=3 virtual nodes
    assert (c.q, c.t, c.nu) == (4, 3, 3)


def test_bad_profiles():
    with pytest.raises(ValueError):
        make(4, 1)
    with pytest.raises(ValueError):
        make(4, 2, d=4)  # d < k+1
    with pytest.raises(ValueError):
        make(4, 2, d=6)  # d > k+m-1
    with pytest.raises(ValueError):
        make(4, 2, gamma=1)


@pytest.mark.parametrize("k,m,d", [(4, 2, 5), (2, 2, 3), (4, 3, 6), (3, 2, 4)])
def test_all_erasure_subsets_roundtrip(k, m, d):
    coder = make(k, m, d)
    full, L = rand_chunks(coder)
    n = k + m
    for r in range(1, m + 1):
        for erased in combinations(range(n), r):
            have = {c: full[c] for c in range(n) if c not in erased}
            rec = coder.decode_chunks(list(erased), have)
            for e in erased:
                np.testing.assert_array_equal(rec[e], full[e], err_msg=f"{erased}")


def test_roundtrip_with_virtual_nodes():
    coder = make(5, 4, 8)  # nu=3
    full, L = rand_chunks(coder)
    for erased in [(0,), (5,), (0, 5), (1, 2, 6, 8), (0, 1, 2, 3)]:
        have = {c: full[c] for c in full if c not in erased}
        rec = coder.decode_chunks(list(erased), have)
        for e in erased:
            np.testing.assert_array_equal(rec[e], full[e], err_msg=f"{erased}")


def test_flagship_geometry_random_erasures():
    coder = make(8, 4, 11)
    full, L = rand_chunks(coder, B=1)
    rng = np.random.default_rng(1)
    for _ in range(6):
        r = int(rng.integers(1, 5))
        erased = tuple(sorted(rng.choice(12, size=r, replace=False).tolist()))
        have = {c: full[c] for c in full if c not in erased}
        rec = coder.decode_chunks(list(erased), have)
        for e in erased:
            np.testing.assert_array_equal(rec[e], full[e], err_msg=f"{erased}")


@pytest.mark.parametrize("k,m,d", [(4, 2, 5), (4, 3, 6), (8, 4, 11)])
def test_repair_single_failure_all_positions(k, m, d):
    coder = make(k, m, d)
    full, L = rand_chunks(coder)
    for failed in range(k + m):
        rebuilt = coder.repair_from_chunks(
            failed, {c: full[c] for c in full if c != failed})
        np.testing.assert_array_equal(rebuilt, full[failed], err_msg=str(failed))


def test_repair_bandwidth_is_msr_optimal():
    # d helpers x beta sub-chunks, beta = subchunks/q -> total read
    # d/(d-k+1) chunk-equivalents, vs k chunks for plain RS.
    k, m, d = 8, 4, 11
    coder = make(k, m, d)
    need = coder.minimum_to_decode_subchunks(0, list(range(1, k + m)))
    assert len(need) == d
    beta = coder.sub_chunk_count // coder.q
    for h, planes in need.items():
        assert len(planes) == beta
    read_fraction = d * beta / (k * coder.sub_chunk_count)
    assert read_fraction == pytest.approx(d / (k * (d - k + 1)))
    assert read_fraction < 0.5  # strictly less than half of RS's k-chunk read


def test_repair_with_virtual_nodes():
    coder = make(5, 4, 8)  # nu=3: exercises virtual partners in repair
    full, L = rand_chunks(coder)
    for failed in range(9):
        rebuilt = coder.repair_from_chunks(
            failed, {c: full[c] for c in full if c != failed})
        np.testing.assert_array_equal(rebuilt, full[failed], err_msg=str(failed))


def test_repair_with_real_nonhelper():
    # d=5 < k+m-1=6: one real chunk sits out of the repair entirely
    coder = make(4, 3, 5)  # k+m=7, q=2 -> t=4, nu=1
    assert coder.q == 2 and coder.nu == 1
    full, L = rand_chunks(coder)
    for failed in range(7):
        need = coder.minimum_to_decode_subchunks(
            failed, [c for c in range(7) if c != failed])
        assert len(need) == coder.d
        picked = {}
        for h, planes in need.items():
            sub = coder._split(full[h])
            picked[h] = sub[..., planes, :]
        rebuilt = coder.repair_chunk(failed, picked)
        np.testing.assert_array_equal(rebuilt, full[failed], err_msg=str(failed))


def test_helper_set_must_cover_failed_column():
    # excluding the failed node's grid-column mate makes the coupled
    # system underdetermined — the plugin must refuse, not corrupt
    coder = make(4, 3, 5)
    failed = 5
    mate = next(c for c in range(7) if c != failed and
                coder._xy(coder._node_of_chunk(c))[1]
                == coder._xy(coder._node_of_chunk(failed))[1])
    bad = tuple(sorted(set(range(7)) - {failed, mate}))[:coder.d]
    assert len(bad) == coder.d
    with pytest.raises(ValueError, match="underdetermined"):
        coder._affine_repair(failed, tuple(bad))
    # and the helper picker always includes the column mate
    picked = coder._pick_helpers(failed, [c for c in range(7) if c != failed])
    assert mate in picked


def test_encode_decode_full_object_api():
    coder = make(4, 2, 5)
    rng = np.random.default_rng(3)
    obj = rng.integers(0, 256, size=4000, dtype=np.uint8).tobytes()
    chunks = coder.encode(list(range(6)), obj)
    rec = coder.decode_concat({c: chunks[c] for c in (0, 2, 4, 5)},
                              object_size=4000)
    assert rec.tobytes() == obj


def test_minimum_to_decode_semantics():
    coder = make(4, 2, 5)
    # no erasure: want itself
    assert coder.minimum_to_decode([0, 1], range(6)) == {0, 1}
    # single erasure with d survivors -> d helpers
    got = coder.minimum_to_decode([0], [1, 2, 3, 4, 5])
    assert len(got) == coder.d and 0 not in got
    # double erasure -> all survivors
    got = coder.minimum_to_decode([0, 1], [2, 3, 4, 5])
    assert got == {2, 3, 4, 5}


def test_mxu_impl_matches_ref():
    import os
    prof_ref = make(4, 2, 5)
    prof_dev = Clay({"k": "4", "m": "2", "d": "5", "impl": "mxu"})
    rng = np.random.default_rng(7)
    L = prof_ref.get_chunk_size(4 * prof_ref.sub_chunk_count * 4)
    data = rng.integers(0, 256, size=(2, 4, L), dtype=np.uint8)
    np.testing.assert_array_equal(
        prof_ref.encode_chunks(data), prof_dev.encode_chunks(data))


def test_decode_with_only_d_helpers_routes_to_repair():
    # the minimum_to_decode -> read -> decode flow for a single erasure
    # hands decode_chunks exactly d chunks; it must produce correct bytes
    coder = make(4, 3, 5)
    full, L = rand_chunks(coder)
    failed = 2
    helpers = coder.minimum_to_decode([failed], [c for c in range(7)
                                                 if c != failed])
    rec = coder.decode_chunks([failed], {h: full[h] for h in helpers})
    np.testing.assert_array_equal(rec[failed], full[failed])


def test_decode_partial_survivors_treated_as_erased():
    # survivors not provided are erased, never silently assumed zero
    coder = make(4, 2, 5)
    full, L = rand_chunks(coder)
    # erase 0, withhold 5: both unknown -> still within m=2, must work
    rec = coder.decode_chunks([0], {c: full[c] for c in (1, 2, 3, 4)})
    np.testing.assert_array_equal(rec[0], full[0])
    assert set(rec) == {0}
    # withholding two more exceeds m -> must raise, not corrupt
    with pytest.raises(ValueError):
        coder.decode_chunks([0], {c: full[c] for c in (1, 2, 3)})


def test_decode_passthrough_of_provided_wanted_chunks():
    # minimum_to_decode with no erasure says "read the chunks themselves";
    # decode_chunks must then return them, not raise
    coder = make(4, 2, 5)
    full, L = rand_chunks(coder)
    got = coder.decode_chunks([0, 1], {0: full[0], 1: full[1]})
    np.testing.assert_array_equal(got[0], full[0])
    np.testing.assert_array_equal(got[1], full[1])
    # mixed: one provided, one missing (degraded read)
    have = {c: full[c] for c in (1, 2, 3, 4)}
    got = coder.decode_chunks([0, 1], have)
    np.testing.assert_array_equal(got[0], full[0])
    np.testing.assert_array_equal(got[1], full[1])
