"""PG split (pg_num increase) tests — the autoscaler's executor
(refs: src/osd/PG.cc split machinery, ceph_stable_mod re-bucketing;
src/mon/OSDMonitor.cc pg_num handling; src/pybind/mgr/pg_autoscaler
`on` mode). Every byte must survive, children must land on their own
CRUSH targets via pg_temp-protected backfill, and a degraded or
quorum-less cluster must refuse to split."""

import numpy as np
import pytest

from ceph_tpu.client.objecter import Objecter
from ceph_tpu.osd.cluster import SimCluster


def make(n_osds=12, pg_num=4, **kw):
    kw.setdefault("profile", "plugin=tpu_rs k=4 m=2 impl=bitlinear")
    c = SimCluster(n_osds=n_osds, pg_num=pg_num, **kw)
    return c, Objecter(c)


def write_corpus(ob, n=60, seed=1, size_lo=50, size_hi=900):
    rng = np.random.default_rng(seed)
    objs = {f"split-{seed}-{i}":
            rng.integers(0, 256, int(rng.integers(size_lo, size_hi)),
                         np.uint8).tobytes() for i in range(n)}
    ob.write(objs)
    return objs


def settle(c, rounds=150):
    for _ in range(rounds):
        if not c.backfills:
            return
        c.tick(6.0)
    raise AssertionError("backfills never drained")


class TestSplit:
    def test_double_preserves_every_byte_and_rebalances(self):
        c, ob = make(pg_num=4)
        objs = write_corpus(ob, n=80)
        before_epoch = c.osdmap.epoch
        rep = c.split_pgs(8)
        assert rep["pg_num"] == 8 and c.pg_num == 8
        assert c.osdmap.epoch > before_epoch       # quorum-gated bump
        assert set(rep["children"]) == {4, 5, 6, 7}
        assert rep["children"] == {4: 0, 5: 1, 6: 2, 7: 3}
        # stable_mod: a healthy split re-homes roughly half the data
        assert 0 < rep["objects_moved"] < len(objs)
        # reads correct IMMEDIATELY (children still on parent OSDs,
        # pg_temp protects the transition)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        # objects live in the PG locate() says, parents kept the rest
        for name in objs:
            ps = c.locate(name)
            assert name in c.pgs[ps].object_sizes
        sizes = [len(c.pgs[ps].object_sizes) for ps in range(8)]
        assert sum(sizes) == len(objs)
        settle(c)
        # children ended on their own CRUSH targets, pg_temp cleared
        for ps in range(8):
            assert c.pgs[ps].acting == c._up(ps), ps
            assert (1, ps) not in c.osdmap.pg_temp
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        # scrub-clean across the board
        for ps in range(8):
            rep = c.pgs[ps].deep_scrub(dead_osds=c._dead_osds())
            assert rep["inconsistent"] == [], ps

    def test_non_power_of_two_target(self):
        c, ob = make(pg_num=4)
        objs = write_corpus(ob, n=40, seed=2)
        c.split_pgs(6)                 # children 4, 5 from parents 0, 1
        assert c.pg_num == 6
        settle(c)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        assert sum(len(c.pgs[ps].object_sizes)
                   for ps in range(6)) == len(objs)

    def test_writes_during_child_backfill_survive(self):
        c, ob = make(pg_num=4)
        first = write_corpus(ob, n=40, seed=3)
        c.split_pgs(8)
        # backfills of children are in flight NOW; write through them
        assert c.backfills
        second = write_corpus(ob, n=40, seed=4)
        settle(c)
        for name, want in {**first, **second}.items():
            assert ob.read(name).tobytes() == want

    def test_split_then_kill_revive_delta_replay_still_exact(self):
        c, ob = make(pg_num=4, down_out_interval=600.0)
        objs = write_corpus(ob, n=40, seed=5)
        c.split_pgs(8)
        settle(c)
        victim = c.pgs[5].acting[0]
        c.kill_osd(victim)
        c.tick(30.0)
        more = write_corpus(ob, n=20, seed=6)
        c.revive_osd(victim)           # PG-log delta replay incl. the
        c.tick(30.0)                   # split's create/delete entries
        for name, want in {**objs, **more}.items():
            assert ob.read(name).tobytes() == want

    def test_refuses_degraded_or_busy_or_shrink(self):
        c, ob = make(pg_num=4, down_out_interval=600.0)
        write_corpus(ob, n=20, seed=7)
        with pytest.raises(ValueError, match="merges"):
            c.split_pgs(4)
        c.kill_osd(c.pgs[0].acting[0])
        with pytest.raises(ValueError, match="degraded"):
            c.split_pgs(8)

    def test_refuses_without_quorum(self):
        c, ob = make(pg_num=4)
        write_corpus(ob, n=10, seed=8)
        c.kill_mon(0)
        c.kill_mon(1)                  # 1 of 3 left: no quorum
        with pytest.raises(ValueError, match="quorum"):
            c.split_pgs(8)
        c.revive_mon(0)
        c.split_pgs(8)                 # quorum back: split proceeds
        assert c.pg_num == 8

    def test_apply_autoscale_executes_recommendation(self):
        # 12 in-OSDs x 100 / size 6 = 200 -> pow2 256; cap it to keep
        # the test fast and prove max_pg_num works
        c, ob = make(pg_num=4)
        objs = write_corpus(ob, n=30, seed=9)
        rep = c.apply_autoscale(max_pg_num=16)
        assert rep is not None and c.pg_num == 16
        settle(c)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        # already at the cap: a second run is a no-op
        assert c.apply_autoscale(max_pg_num=16) is None

    def test_split_on_persistent_store(self, tmp_path):
        c, ob = make(pg_num=4, store="tin",
                     store_dir=str(tmp_path / "osds"))
        objs = write_corpus(ob, n=30, seed=10)
        c.split_pgs(8)
        settle(c)
        # the split survives SIGKILL of every OSD: WAL replay rebuilds
        # parent AND child collections
        for o in list(c.cluster.stores):
            c.cluster.stores[o].crash()
            c.cluster.stores[o].remount()
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want

    @pytest.mark.slow   # ~27 s; EC-pool split stays tier-1 (r10)
    def test_replicated_pool_splits_too(self):
        c, ob = make(pg_num=4, profile="replicated size=3", n_osds=9)
        objs = write_corpus(ob, n=40, seed=11)
        c.split_pgs(8)
        settle(c)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
