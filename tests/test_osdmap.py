"""OSDMap tests: stable-mod properties, object->PG->OSD pipeline,
pg_temp/primary_temp overrides, batched == scalar parity."""

import numpy as np
import pytest

from ceph_tpu.crush.map import (CRUSH_ITEM_NONE, Tunables, build_hierarchy,
                                ec_rule, replicated_rule)
from ceph_tpu.osd.osdmap import (OSDMap, PGPool, ceph_stable_mod,
                                 pg_num_mask, str_hash_rjenkins)


def make_osdmap(n_osds=32):
    m = build_hierarchy(n_osds, 4, 4)
    m.tunables = Tunables(choose_total_tries=7)
    replicated_rule(m, 0, choose_type=1, firstn=True)
    ec_rule(m, 1, choose_type=1)
    om = OSDMap(m)
    om.add_pool(PGPool(1, pg_num=64, size=3, min_size=2, crush_rule=0))
    om.add_pool(PGPool(2, pg_num=64, size=6, min_size=5, crush_rule=1,
                       is_erasure=True, ec_profile={"k": "4", "m": "2"}))
    return om


def test_stable_mod_basics():
    # within range, identity-ish; doubling pg_num only remaps new half
    for pg_num in (1, 3, 8, 12, 100):
        mask = pg_num_mask(pg_num)
        for x in range(500):
            v = ceph_stable_mod(x, pg_num, mask)
            assert 0 <= v < pg_num
    # array form agrees with scalar
    xs = np.arange(1000)
    got = ceph_stable_mod(xs, 12, pg_num_mask(12))
    want = [ceph_stable_mod(int(x), 12, pg_num_mask(12)) for x in xs]
    assert got.tolist() == want


def test_stable_mod_split_stability():
    # growing pg_num from 8 to 16: objects whose (x & 15) < 8 keep their pg
    m8, m16 = pg_num_mask(8), pg_num_mask(16)
    for x in range(2000):
        before = ceph_stable_mod(x, 8, m8)
        after = ceph_stable_mod(x, 16, m16)
        assert after % 8 == before


def test_str_hash_deterministic():
    h1 = str_hash_rjenkins("rbd_data.12345")
    h2 = str_hash_rjenkins(b"rbd_data.12345")
    assert h1 == h2
    assert h1 != str_hash_rjenkins("rbd_data.12346")
    assert 0 <= h1 < 2 ** 32
    # all tail lengths exercise the switch
    seen = {str_hash_rjenkins("x" * n) for n in range(30)}
    assert len(seen) == 30


def test_object_to_pg_and_up():
    om = make_osdmap()
    pg = om.object_to_pg(1, "obj-1")
    assert pg[0] == 1 and 0 <= pg[1] < 64
    up, upp, acting, actp = om.pg_to_up_acting_osds(*pg)
    assert len(up) == 3
    assert upp == up[0] and actp == acting[0]
    assert all(0 <= o < 32 for o in up)


@pytest.mark.slow   # ~17 s full-map parity sweep; nightly (r10)
def test_batched_matches_scalar():
    om = make_osdmap()
    for pool_id in (1, 2):
        batched = om.pgs_to_up(pool_id)
        pool = om.pools[pool_id]
        for ps in range(0, 64, 7):
            up, *_ = om.pg_to_up_acting_osds(pool_id, ps)
            assert batched[ps].tolist() == up, f"pool={pool_id} ps={ps}"


def test_down_osd_leaves_hole_in_up():
    om = make_osdmap()
    up0 = om.pgs_to_up(2)
    victim = int(up0[0, 0])
    om.mark_down(victim)
    up1 = om.pgs_to_up(2)
    assert not (up1 == victim).any()
    # down (not out) keeps placement for other slots: only holes differ
    changed = (up0 != up1)
    assert (up0[changed] == victim).all()


def test_out_osd_remaps():
    om = make_osdmap()
    up0 = om.pgs_to_up(1)
    victim = int(up0[0, 0])
    om.mark_out(victim)
    up1 = om.pgs_to_up(1)
    assert not (up1 == victim).any()
    assert (up1 != CRUSH_ITEM_NONE).all()  # replicas found elsewhere


def test_pg_temp_and_primary_temp():
    om = make_osdmap()
    pg = (1, 5)
    up, upp, acting, actp = om.pg_to_up_acting_osds(*pg)
    override = [(upp + 1) % 32, (upp + 2) % 32, (upp + 3) % 32]
    om.set_pg_temp(pg, override)
    om.set_primary_temp(pg, override[1])
    up2, upp2, acting2, actp2 = om.pg_to_up_acting_osds(*pg)
    assert up2 == up          # up unaffected
    assert acting2 == override
    assert actp2 == override[1]
    # batched: up ignores pg_temp, acting applies it (same as scalar)
    assert om.pgs_to_up(1)[5].tolist() == up
    assert om.pgs_to_acting(1)[5].tolist() == override
    # clearing restores
    om.set_pg_temp(pg, [])
    om.set_primary_temp(pg, None)
    assert om.pg_to_up_acting_osds(*pg)[2] == up


def test_epoch_bumps():
    om = make_osdmap()
    e0 = om.epoch
    om.mark_down(0)
    om.mark_out(0)
    assert om.epoch == e0 + 2


def test_pg_stats_balance():
    om = make_osdmap()
    stats = om.pg_stats(1)
    assert stats["degraded_pgs"] == 0
    counts = stats["pg_per_osd"]
    assert counts.sum() == 64 * 3
    assert counts.max() <= 4 * counts.mean()  # no pathological skew


def test_up_thru_records_and_roundtrips():
    """up_thru (ref: osd_info_t::up_thru): monotone, idempotent,
    refused for down OSDs, and carried through the v6 wire form."""
    om = make_osdmap()
    e0 = om.epoch
    om.record_up_thru(3)                  # defaults to current epoch
    assert int(om.osd_up_thru[3]) == e0
    assert om.epoch == e0 + 1
    om.record_up_thru(3, e0 - 1)          # stale claim: no-op
    assert int(om.osd_up_thru[3]) == e0 and om.epoch == e0 + 1
    om.record_up_thru(7, e0 + 1)
    om.mark_down(5)
    e1 = om.epoch
    om.record_up_thru(5)                  # down OSD: refused
    assert int(om.osd_up_thru[5]) == 0 and om.epoch == e1
    # wire round-trip preserves the whole array
    om2 = OSDMap.decode(om.encode())
    assert om2.osd_up_thru.tolist() == om.osd_up_thru.tolist()
    assert int(om2.osd_up_thru[3]) == e0
    assert int(om2.osd_up_thru[7]) == e0 + 1


def test_pool_validation():
    om = make_osdmap()
    with pytest.raises(ValueError):
        om.add_pool(PGPool(3, pg_num=8, size=3, min_size=2, crush_rule=99))
