"""CRUSH text compile/decompile round-trip (ref: src/crush/
CrushCompiler.cc; crushtool -c / -d workflows)."""

import subprocess
import sys

import numpy as np
import pytest

from ceph_tpu.crush.compiler import CompileError, compile_text, decompile
from ceph_tpu.crush.map import (CrushMap, Tunables, build_hierarchy,
                                ec_rule, replicated_rule)
from ceph_tpu.crush.mapper import VectorMapper, full_weights


def built_map(alg="straw2"):
    m = build_hierarchy(24, osds_per_host=3, hosts_per_rack=4, alg=alg)
    m.tunables = Tunables(choose_total_tries=19)
    replicated_rule(m, 0, choose_type=1, firstn=True)
    ec_rule(m, 1, choose_type=1)
    return m


# one alg (the modern default) stays tier-1; the full sweep is the
# nightly's (-m slow) — each cell costs ~36 s of the 870 s cap (r10)
@pytest.mark.parametrize("alg", [
    "straw2",
    pytest.param("tree", marks=pytest.mark.slow),
    pytest.param("straw", marks=pytest.mark.slow),
    pytest.param("list", marks=pytest.mark.slow)])
def test_roundtrip_places_identically(alg):
    m = built_map(alg)
    m2 = compile_text(decompile(m))
    assert m2.tunables.choose_total_tries == 19
    assert m2.root_id == m.root_id
    w = full_weights(24)
    xs = np.arange(300, dtype=np.uint32)
    for rule in (0, 1):
        a = np.asarray(VectorMapper(m).do_rule(rule, xs, w, 4))
        b = np.asarray(VectorMapper(m2).do_rule(rule, xs, w, 4))
        assert np.array_equal(a, b), alg


def test_text_is_stable_fixpoint():
    m = built_map()
    t1 = decompile(m)
    t2 = decompile(compile_text(t1))
    assert t1 == t2


def test_handwritten_map_compiles():
    text = """
# comment
tunable choose_total_tries 13
device 0 osd.0
device 1 osd.1
device 2 osd.2
device 3 osd.3
type 0 osd
type 1 host
type 2 root
host ha {
    id -1
    alg straw2
    hash 0
    item osd.0 weight 1.000
    item osd.1 weight 2.000
}
host hb {
    id -2
    alg straw2
    hash 0
    item osd.2 weight 1.000
    item osd.3 weight 1.000
}
root default {
    id -3
    alg straw2
    hash 0
    item ha weight 3.000
    item hb weight 2.000
}
rule data {
    id 0
    type replicated
    min_size 1
    max_size 10
    step take default
    step chooseleaf firstn 0 type host
    step emit
}
"""
    m = compile_text(text)
    assert m.tunables.choose_total_tries == 13
    assert m.root_id == -3
    assert m.buckets[-1].weights == [0x10000, 0x20000]
    got = np.asarray(VectorMapper(m).do_rule(0, np.arange(200,
                                                          dtype=np.uint32),
                                             full_weights(4), 2))
    # two replicas on distinct hosts
    hosts = np.where(got < 2, 0, 1)
    assert (hosts[:, 0] != hosts[:, 1]).all()


@pytest.mark.parametrize("bad,msg", [
    ("bogus directive", "unknown directive"),
    ("type 1 host\nhost h {\n id -1\n}", "no alg"),
    ("type 1 host\nhost h {\n alg straw2\n}", "no id"),
    ("type 1 host\nhost h {\n id -1\n alg warp\n}", "unknown alg"),
    ("rule r {\n id 0\n step emit\n}", "must start with take"),
    ("type 1 host\nhost h {\n id -1\n alg straw2\n item nope\n}",
     "unknown item"),
])
def test_bad_text_rejected(bad, msg):
    with pytest.raises((CompileError, ValueError), match=msg):
        compile_text(bad)


def test_cli_compile_decompile_roundtrip(tmp_path):
    m = built_map()
    txt = tmp_path / "map.txt"
    txt.write_text(decompile(m))
    binf = tmp_path / "map.bin"
    r = subprocess.run(
        [sys.executable, "tools/crushtool.py", "-c", str(txt),
         "-o", str(binf)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert binf.exists()
    r2 = subprocess.run(
        [sys.executable, "tools/crushtool.py", "-d", str(binf)],
        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 0, r2.stderr
    assert r2.stdout == decompile(m)
