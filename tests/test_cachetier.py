"""Cache tiering over SimCluster pools (ref: PrimaryLogPG
maybe_handle_cache_detail / agent_work; qa cache-tier workflows).
The cache pool is a small replicated cluster, the base an EC pool —
the canonical fast-tier-over-EC deployment."""

import numpy as np
import pytest

from ceph_tpu.osd.cachetier import CacheTier
from cluster_helpers import make_cluster


def mk_tier(**kw):
    base = make_cluster(n_osds=8, pg_num=4)
    cache = make_cluster(n_osds=4, pg_num=2,
                         profile="replicated size=2")
    kw.setdefault("target_max_bytes", 64 * 1024)
    tier = CacheTier(base, cache, **kw)
    return tier, base, cache


def blob(i, size=1000):
    rng = np.random.default_rng(i)
    return rng.integers(0, 256, size, np.uint8)


class TestWritebackPath:
    def test_write_lands_in_cache_only_until_flush(self):
        tier, base, cache = mk_tier()
        data = blob(1)
        tier.write({"a": data})
        np.testing.assert_array_equal(tier.read("a"), data)
        with pytest.raises(KeyError):
            base.read("a")          # writeback: base not written yet
        assert tier.dirty_bytes == 1000
        tier.flush()
        np.testing.assert_array_equal(np.asarray(base.read("a")), data)
        assert tier.dirty_bytes == 0
        # still served from cache (clean hit)
        np.testing.assert_array_equal(tier.read("a"), data)
        assert tier.stats()["tier_hit"] >= 2

    def test_overwrite_redirties(self):
        tier, base, _ = mk_tier()
        tier.write({"a": blob(1)})
        tier.flush()
        new = blob(2)
        tier.write({"a": new})
        assert tier.dirty_bytes == 1000
        tier.flush()
        np.testing.assert_array_equal(np.asarray(base.read("a")), new)


class TestPromotionAndProxy:
    def test_miss_proxies_then_promotes(self):
        tier, base, cache = mk_tier(promote_after_hits=2)
        data = blob(3)
        base.write({"cold": data})
        # first read: proxy (not cached)
        np.testing.assert_array_equal(tier.read("cold"), data)
        assert tier.stats()["tier_proxy_read"] == 1
        assert tier.stats()["objects"] == 0
        # second read within the period: promote
        np.testing.assert_array_equal(tier.read("cold"), data)
        assert tier.stats()["tier_promote"] == 1
        assert tier.stats()["objects"] == 1
        # third read is a cache hit
        tier.read("cold")
        assert tier.stats()["tier_hit"] == 1

    def test_hit_set_decay_blocks_slow_scans(self):
        tier, base, _ = mk_tier(promote_after_hits=2,
                                hit_set_period=2)
        base.write({"x": blob(4), "y": blob(5), "z": blob(6)})
        # one touch each: the decay window expires between repeats,
        # so a slow scan never accumulates enough hits to promote
        for _ in range(3):
            tier.read("x"), tier.read("y"), tier.read("z")
        assert tier.stats()["tier_promote"] == 0

    def test_missing_object_raises(self):
        tier, _, _ = mk_tier()
        with pytest.raises(KeyError):
            tier.read("nope")


class TestAgent:
    def test_agent_flushes_dirty_over_ratio(self):
        tier, base, _ = mk_tier(target_max_bytes=8000,
                                dirty_ratio=0.4, full_ratio=1.0)
        objs = {f"d{i}": blob(10 + i) for i in range(8)}  # 8000 dirty
        tier.write(objs)
        # agent must have flushed down to <= 3200 dirty
        assert tier.dirty_bytes <= 3200
        for name, data in objs.items():
            got = tier.read(name) if name in tier._size \
                else np.asarray(base.read(name))
            np.testing.assert_array_equal(got, data, err_msg=name)

    def test_agent_evicts_cold_clean_over_full_ratio(self):
        tier, base, _ = mk_tier(target_max_bytes=8000,
                                dirty_ratio=0.1, full_ratio=0.5)
        objs = {f"e{i}": blob(20 + i) for i in range(8)}
        tier.write(objs)
        assert tier.cache_bytes <= 4000
        # every byte still readable through the tier (refetch on miss)
        for name, data in objs.items():
            np.testing.assert_array_equal(tier.read(name), data)
        assert tier.stats()["tier_evict"] >= 1

    def test_flush_evict_all_drains(self):
        tier, base, cache = mk_tier()
        objs = {f"f{i}": blob(30 + i) for i in range(4)}
        tier.write(objs)
        tier.flush_evict_all()
        assert tier.stats()["objects"] == 0
        assert tier.cache_bytes == 0
        for name, data in objs.items():
            np.testing.assert_array_equal(np.asarray(base.read(name)),
                                          data)


class TestWhiteouts:
    def test_delete_dirty_object_propagates_on_flush(self):
        tier, base, _ = mk_tier()
        tier.write({"w": blob(7)})
        tier.flush()                      # now in base too
        tier.write({"w": blob(8)})        # dirty again
        tier.remove("w")
        with pytest.raises(KeyError):
            tier.read("w")                # whiteout hides base copy
        np.asarray(base.read("w"))        # base still has old bytes
        tier.flush()
        with pytest.raises(KeyError):
            base.read("w")                # delete reached the base
        with pytest.raises(KeyError):
            tier.read("w")

    def test_delete_cache_only_object(self):
        tier, base, _ = mk_tier()
        tier.write({"c": blob(9)})
        tier.remove("c")                  # never reached base
        with pytest.raises(KeyError):
            tier.read("c")
        tier.flush()                      # no whiteout explosion
        with pytest.raises(KeyError):
            base.read("c")

    def test_remove_unknown_raises(self):
        tier, _, _ = mk_tier()
        with pytest.raises(KeyError):
            tier.remove("ghost")

    def test_rewrite_after_whiteout(self):
        tier, base, _ = mk_tier()
        tier.write({"r": blob(11)})
        tier.flush()
        tier.remove("r")
        fresh = blob(12)
        tier.write({"r": fresh})          # write clears the whiteout
        np.testing.assert_array_equal(tier.read("r"), fresh)
        tier.flush()
        np.testing.assert_array_equal(np.asarray(base.read("r")), fresh)
