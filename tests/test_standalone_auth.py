"""cephx over the wire tier: monitor-issued tickets, OSD session
authorization, caps enforcement, secret rotation (refs:
src/auth/cephx/CephxProtocol.cc, src/mon/AuthMonitor.cc,
OSD::ms_verify_authorizer, OSDCap::is_capable)."""

import numpy as np
import pytest

from ceph_tpu.auth import AuthError
from ceph_tpu.osd.standalone import StandaloneCluster


@pytest.fixture
def cluster():
    c = StandaloneCluster(n_osds=3, pg_num=2, op_timeout=3.0,
                          cephx=True)
    try:
        c.wait_for_clean(timeout=20)
        yield c
    finally:
        c.shutdown()


def corpus(seed, n=6):
    rng = np.random.default_rng(seed)
    return {f"authobj-{seed}-{i}":
            rng.integers(0, 256, 300, np.uint8).tobytes()
            for i in range(n)}


class TestCephxWire:
    def test_admin_io_authenticates_transparently(self, cluster):
        """First op hits EPERM:unauthenticated, the client runs the
        full ticket dance over MAuthOp frames, the op retries and
        succeeds — and the data is bytes-exact."""
        cl = cluster.client()
        objs = corpus(1)
        cl.write(objs)
        for name, want in objs.items():
            assert cl.read(name) == want
        # sessions actually exist on the daemons
        assert any(d._authed for d in cluster.osds.values())

    def test_wrong_secret_cannot_login(self, cluster):
        cl = cluster.client(secret=b"\x00" * 32)
        with pytest.raises(AuthError, match="bad proof"):
            cl.write(corpus(2))

    def test_unknown_entity_rejected(self, cluster):
        cl = cluster.client(entity="client.ghost",
                            secret=b"\x01" * 32)
        with pytest.raises(AuthError, match="unknown entity"):
            cl.write(corpus(3))

    def test_readonly_caps_enforced(self, cluster):
        admin = cluster.client()
        objs = corpus(4)
        admin.write(objs)
        ro_secret = cluster.create_entity(
            "client.reader", caps={"mon": "allow r",
                                   "osd": "allow r"})
        ro = cluster.client(entity="client.reader", secret=ro_secret)
        name = next(iter(objs))
        assert ro.read(name) == objs[name]
        with pytest.raises(PermissionError, match="denied need w"):
            ro.write({name: b"overwrite attempt"})
        # the object is untouched
        assert admin.read(name) == objs[name]

    def test_revived_osd_requires_reauth_and_serves(self, cluster):
        """Auth sessions die with the daemon process; after revive the
        client transparently re-authorizes and I/O still works."""
        cl = cluster.client()
        objs = corpus(5)
        cl.write(objs)
        victim = cluster.osd_ids()[0]
        cluster.kill_osd(victim)
        cluster.revive_osd(victim)
        assert cluster.osds[victim]._authed == {}
        more = corpus(6)
        cl.write(more)
        for name, want in {**objs, **more}.items():
            assert cl.read(name) == want

    def test_pool_scoped_caps_match_the_pool(self, cluster):
        """`allow rw pool=default` works against the tier's pool;
        `allow rw pool=other` does not."""
        admin = cluster.client()
        objs = corpus(8)
        admin.write(objs)
        ok_secret = cluster.create_entity(
            "client.pooled", caps={"mon": "allow r",
                                   "osd": "allow rw pool=default"})
        pooled = cluster.client(entity="client.pooled",
                                secret=ok_secret)
        name = next(iter(objs))
        assert pooled.read(name) == objs[name]
        bad_secret = cluster.create_entity(
            "client.wrongpool", caps={"mon": "allow r",
                                      "osd": "allow rw pool=other"})
        wrong = cluster.client(entity="client.wrongpool",
                               secret=bad_secret)
        with pytest.raises(PermissionError):
            wrong.read(name)

    def test_mon_admin_plane_gated(self, cluster):
        """Pool snapshots need a mon ticket with w: the read-only
        entity's mksnap broadcast is dropped (commit-wait times out);
        the admin's goes through."""
        admin = cluster.client()
        admin.write(corpus(9))
        ro_secret = cluster.create_entity(
            "client.monro", caps={"mon": "allow r",
                                  "osd": "allow r"})
        ro = cluster.client(entity="client.monro", secret=ro_secret)
        with pytest.raises(TimeoutError):
            ro.snap_create("sneaky", timeout=2.0)
        sid = admin.snap_create("legit")
        assert sid >= 1

    def test_store_plane_rejects_unauthenticated_frames(self, cluster):
        """Raw MStoreOp frames from a peer with no session bounce with
        EPERM — the data plane can't be reached around the op gate."""
        from ceph_tpu.osd.standalone import (MStoreReply, RemoteStore,
                                             _Rpc)
        admin = cluster.client()
        admin.write(corpus(10))
        # a FRESH endpoint that has never authorized anything: its
        # raw store frames must bounce (sessions are per-peer; the
        # admin's session must not bleed onto this messenger)
        cl = cluster.client()
        target = f"osd.{cluster.osd_ids()[0]}"
        rs = RemoteStore(_Rpc(cl.msgr, MStoreReply.type_id), target,
                         timeout=3.0)  # no authorize callback
        import re
        with pytest.raises(ConnectionError,
                           match=re.escape("EPERM:unauthenticated")):
            rs.list_objects("meta")

    @pytest.mark.slow   # ~22 s thrash cell; nightly (r10 cap fix)
    def test_auth_survives_thrash_rotation_and_partition(self):
        """cephx under chaos: OSD kill/revive, repeated secret
        rotation, and a monitor partition — client I/O keeps flowing
        through transparent re-auth, and every byte survives."""
        import numpy as np
        c = StandaloneCluster(n_osds=4, pg_num=2, op_timeout=3.0,
                              cephx=True)
        try:
            c.wait_for_clean(timeout=20)
            cl = c.client()
            rng = np.random.default_rng(42)
            data: dict[str, bytes] = {}
            for rnd in range(3):
                objs = {f"chaos-{rnd}-{i}":
                        rng.integers(0, 256, 256, np.uint8).tobytes()
                        for i in range(4)}
                cl.write(objs)
                data.update(objs)
                victim = rnd % 4
                c.kill_osd(victim)           # sessions at victim die
                c.rotate_service_secrets("osd")
                if rnd == 1:
                    # a partitioned minority monitor must not break
                    # the auth plane (clients hunt the majority side)
                    c.partition({"mon.2"}, {"mon.0", "mon.1"})
                # write once the quorum has marked the death (the
                # established tier pattern: availability DURING
                # detection is its own suite; this test is about auth
                # riding failure + rotation + partition)
                c._wait(lambda: any(
                    not m._stop.is_set() and m.osdmap is not None
                    and not m.osdmap.osd_up[victim]
                    for m in c.mons), 25, f"osd.{victim} marked down")
                more = {f"chaos-{rnd}-deg-{i}":
                        rng.integers(0, 256, 256, np.uint8).tobytes()
                        for i in range(2)}
                cl.write(more)               # degraded + rotated
                data.update(more)
                if rnd == 1:
                    c.heal_partition()
                c.revive_osd(victim)         # fresh verifier, no
                #                              sessions: forces re-auth
                # recover before the next injection (the qa thrasher's
                # wait_for_clean between disruptions): with k=2 m=1 a
                # second loss during recovery would legitimately drop
                # below min_size — that's durability math, not auth
                c.wait_for_clean(timeout=40)
            for k, want in data.items():
                assert cl.read(k) == want
            # a brand-new client after 3 rotations: the boot-era
            # tickets are long rotated out; the full login + fetch
            # chain must still converge
            cl2 = c.client()
            probe = next(iter(data))
            assert cl2.read(probe) == data[probe]
        finally:
            c.shutdown()

    def test_cephx_on_tinstore_survives_sigkill(self, tmp_path):
        """Cross-feature: ticket auth over a PERSISTENT store — a
        SIGKILLed+revived OSD remounts from WAL, re-fetches rotating
        secrets, and serves the same bytes to re-authenticated
        clients."""
        import numpy as np
        c = StandaloneCluster(n_osds=3, pg_num=2, op_timeout=3.0,
                              cephx=True, store="tin",
                              store_dir=str(tmp_path))
        try:
            c.wait_for_clean(timeout=20)
            cl = c.client()
            rng = np.random.default_rng(7)
            objs = {f"tin-{i}":
                    rng.integers(0, 256, 400, np.uint8).tobytes()
                    for i in range(8)}
            cl.write(objs)
            victim = c.osd_ids()[0]
            c.kill_osd(victim)       # REAL process death: RAM dropped
            c.revive_osd(victim)     # WAL remount + fresh verifier
            c.wait_for_clean(timeout=40)
            for name, want in objs.items():
                assert cl.read(name) == want
        finally:
            c.shutdown()

    def test_thrash_with_injection_knobs_cephx_secure(self, tmp_path):
        """The full-composition chaos cell: ms_inject_socket_failures
        + ms_inject_delay live on every OSD, cephx tickets AND secure
        (encrypted) wire mode on, persistent TinStore under the
        daemons — kill/revive thrash must keep every byte through
        reconnect+replay, re-auth, and WAL remount all at once."""
        import numpy as np
        c = StandaloneCluster(n_osds=4, pg_num=2, op_timeout=6.0,
                              cephx=True, secret=b"\x42" * 32,
                              store="tin", store_dir=str(tmp_path))
        try:
            c.wait_for_clean(timeout=25)
            # every Nth send tears the socket down; every Mth send
            # sleeps — the r5 injection knobs, now composed with the
            # auth + secure + persistence planes instead of isolated
            c.inject_socket_failures(9)
            c.inject_delays(6, 8.0)
            cl = c.client()
            rng = np.random.default_rng(11)
            data: dict[str, bytes] = {}
            for rnd in range(2):
                objs = {f"inj-{rnd}-{i}":
                        rng.integers(0, 256, 300, np.uint8).tobytes()
                        for i in range(4)}
                cl.write(objs)
                data.update(objs)
                victim = c.osd_ids()[rnd % 4]
                c.kill_osd(victim)
                c._wait(lambda: any(
                    not m._stop.is_set() and m.osdmap is not None
                    and not m.osdmap.osd_up[victim]
                    for m in c.mons), 25, f"osd.{victim} marked down")
                more = {f"inj-{rnd}-deg-{i}":
                        rng.integers(0, 256, 300, np.uint8).tobytes()
                        for i in range(2)}
                cl.write(more)           # degraded, through injection
                data.update(more)
                c.revive_osd(victim)     # WAL remount + re-auth; the
                c.inject_socket_failures(9, osds=[victim])  # revived
                c.inject_delays(6, 8.0, osds=[victim])      # daemon
                #                          rejoins the injection matrix
                c.wait_for_clean(timeout=50)
            for name, want in sorted(data.items()):
                assert cl.read(name) == want
        finally:
            c.inject_socket_failures(0)
            c.inject_delays(0, 0.0)
            c.shutdown()

    def test_rotation_keep_window_then_refresh(self, cluster):
        cl = cluster.client()
        objs = corpus(7)
        cl.write(objs)                       # sessions established
        # rotate within the keep-window: existing tickets stay valid
        cluster.rotate_service_secrets("osd")
        name = next(iter(objs))
        assert cl.read(name) == objs[name]
        # rotate past the window: daemons refuse old tickets; a fresh
        # client (new sessions forced) must transparently re-fetch
        cluster.rotate_service_secrets("osd")
        cluster.rotate_service_secrets("osd")
        cl2 = cluster.client()
        assert cl2.read(name) == objs[name]
        cl2.write({name: b"post-rotation write"})
        assert cl.read(name) == b"post-rotation write"


class TestAdminSocketCaps:
    def test_admin_commands_respect_caps(self, cluster):
        """Daemon admin commands ride the same caps gate as reads:
        a reader entity may `perf dump`; a mon-only entity (no osd
        caps) is refused with the _op PermissionError contract."""
        admin = cluster.client()
        admin.write(corpus(9, n=3))
        osd = cluster.osd_ids()[0]
        ro_secret = cluster.create_entity(
            "client.obsv", caps={"mon": "allow r", "osd": "allow r"})
        ro = cluster.client(entity="client.obsv", secret=ro_secret)
        perf = ro.daemon(osd, "perf dump")
        assert f"osd.{osd}" in perf
        no_osd_secret = cluster.create_entity(
            "client.monly", caps={"mon": "allow r"})
        blocked = cluster.client(entity="client.monly",
                                 secret=no_osd_secret)
        with pytest.raises(PermissionError):
            blocked.daemon(osd, "perf dump")
