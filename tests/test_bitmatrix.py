"""Bitmatrix technique tests: liberation / blaum_roth / liber8tion
(ref: TestErasureCodeJerasure.cc per-technique suites — build from
profile, encode random buffers, erase every <= m subset, decode,
byte-compare)."""

import numpy as np
import pytest

from ceph_tpu.ec.bitmatrix import (JerasureBitmatrix, blaum_roth_bitmatrix,
                                   bitmatrix_decode_matrix, gf2_inv,
                                   liber8tion_bitmatrix,
                                   liberation_bitmatrix)
from ceph_tpu.ec.registry import factory
from ceph_tpu.ops.xor_kernels import make_xor_encoder, xor_schedule_ref

from itertools import combinations


class TestConstructions:
    def test_liberation_shapes_and_density(self):
        bm = liberation_bitmatrix(5, 7)
        assert bm.shape == (14, 35)
        # P row-block: k identities
        assert bm[:7, :7].tolist() == np.eye(7, dtype=int).tolist()
        # Q blocks: rotation + <=1 extra
        for j in range(5):
            blk = bm[7:, j * 7:(j + 1) * 7]
            assert blk.sum() in (7, 8)

    def test_liberation_requires_prime_w(self):
        with pytest.raises(ValueError):
            liberation_bitmatrix(4, 8)

    def test_blaum_roth_requires_w_plus_1_prime(self):
        with pytest.raises(ValueError):
            blaum_roth_bitmatrix(4, 7)  # 8 not prime
        bm = blaum_roth_bitmatrix(4, 6)  # 7 prime
        assert bm.shape == (12, 24)

    def test_liber8tion_deterministic(self):
        a = liber8tion_bitmatrix(8)
        b = liber8tion_bitmatrix(8)
        assert np.array_equal(a, b)
        assert a.shape == (16, 64)

    def test_gf2_inv_roundtrip(self):
        rng = np.random.default_rng(0)
        for _ in range(10):
            while True:
                m = rng.integers(0, 2, size=(9, 9), dtype=np.uint8)
                try:
                    inv = gf2_inv(m)
                    break
                except ValueError:
                    continue
            assert np.array_equal((m @ inv) & 1, np.eye(9, dtype=np.uint8))


PROFILES = [
    ("liberation", 7, 5),
    ("liberation", 7, 7),
    ("liberation", 11, 4),
    ("blaum_roth", 6, 4),
    ("blaum_roth", 6, 6),
    ("blaum_roth", 10, 5),
    ("liber8tion", 8, 4),
    ("liber8tion", 8, 8),
]

# The widest geometry per technique moves to the nightly (~17-20 s
# each: C(n,2)+C(n,1) erasure subsets); the narrower cells keep the
# technique covered in tier-1 (liber8tion: r10 cap fix; liberation:
# r19 cap fix). PROFILES itself stays plain tuples — other tests
# slice it.
_NIGHTLY = {("liberation", 7, 7), ("liber8tion", 8, 8)}
ROUNDTRIP_PARAMS = [
    pytest.param(*p, marks=pytest.mark.slow) if p in _NIGHTLY else p
    for p in PROFILES
]


class TestRoundTrip:
    @pytest.mark.parametrize("technique,w,k", ROUNDTRIP_PARAMS)
    def test_erase_every_le_m_subset(self, technique, w, k):
        coder = factory({"plugin": "jerasure", "technique": technique,
                         "k": str(k), "m": "2", "w": str(w)})
        assert isinstance(coder, JerasureBitmatrix)
        cs = coder.get_chunk_size(1)
        rng = np.random.default_rng(hash((technique, w, k)) % 2**32)
        data = rng.integers(0, 256, size=(2, k, cs), dtype=np.uint8)
        parity = coder.encode_chunks(data)
        assert parity.shape == (2, 2, cs)
        full = {i: data[:, i] for i in range(k)}
        full.update({k + i: parity[:, i] for i in range(2)})
        n = k + 2
        for r in (1, 2):
            for erased in combinations(range(n), r):
                have = {i: full[i] for i in range(n) if i not in erased}
                rec = coder.decode(list(erased), have)
                for e in erased:
                    np.testing.assert_array_equal(
                        rec[e], full[e],
                        err_msg=f"{technique} erased={erased}")

    def test_device_kernel_matches_oracle(self):
        bm = liberation_bitmatrix(5, 7)
        rng = np.random.default_rng(1)
        data = rng.integers(0, 256, size=(3, 5, 7 * 32), dtype=np.uint8)
        got = np.asarray(make_xor_encoder(bm, 7)(data))
        exp = xor_schedule_ref(bm, 7, data)
        np.testing.assert_array_equal(got, exp)

    def test_decode_matrix_identity_for_data_survivors(self):
        bm = blaum_roth_bitmatrix(4, 6)
        D = bitmatrix_decode_matrix(bm, 4, 6, [4], list(range(4)))
        # parity P from all-data survivors == Q-row... P row = XOR of all
        got = (D.sum(axis=1) & 1)
        assert D.shape == (6, 24)

    def test_p_parity_is_pure_xor(self):
        for technique, w, k in PROFILES[:3]:
            coder = factory({"plugin": "jerasure", "technique": technique,
                             "k": str(k), "m": "2", "w": str(w)})
            cs = coder.get_chunk_size(1)
            rng = np.random.default_rng(2)
            data = rng.integers(0, 256, size=(1, k, cs), dtype=np.uint8)
            parity = coder.encode_chunks(data)
            want_p = data[0, 0].copy()
            for j in range(1, k):
                want_p ^= data[0, j]
            np.testing.assert_array_equal(parity[0, 0], want_p)


class TestLiber8tionCrossCheck:
    def test_bitlane_symbols_match_r6_gf_math(self):
        """liber8tion's XOR schedule == generator-2 RAID-6 over
        bit-sliced symbols: lane t of the 8 packet columns is a GF(2^8)
        symbol; parity lane t must be P (XOR) and Q (sum of 2^j * s_j)."""
        from ceph_tpu.gf.numpy_ref import gf_mul
        from ceph_tpu.gf.tables import gf_pow_scalar
        k = 5
        coder = JerasureBitmatrix({"technique": "liber8tion",
                                   "k": str(k), "m": "2"})
        cs = coder.get_chunk_size(1)
        pkt = cs // 8
        rng = np.random.default_rng(9)
        data = rng.integers(0, 256, size=(1, k, cs), dtype=np.uint8)
        parity = coder.encode_chunks(data)

        def symbols(chunk):  # (cs,) -> (8, pkt) uint8 lane-symbols
            pk = chunk.reshape(8, pkt)  # packet rows
            out = np.zeros((8, pkt), dtype=np.uint8)
            for t in range(8):  # bit-lane t
                lane = (pk >> t) & 1          # (8, pkt) bits
                out[t] = sum(lane[b].astype(np.uint8) << b for b in range(8))
            return out

        ds = [symbols(data[0, j]) for j in range(k)]
        p_sym = symbols(parity[0, 0])
        q_sym = symbols(parity[0, 1])
        want_p = ds[0].copy()
        for j in range(1, k):
            want_p ^= ds[j]
        np.testing.assert_array_equal(p_sym, want_p)
        want_q = np.zeros_like(q_sym)
        for j in range(k):
            c = np.uint8(gf_pow_scalar(2, j))
            want_q ^= gf_mul(np.full_like(ds[j], c), ds[j])
        np.testing.assert_array_equal(q_sym, want_q)


class TestBackendIntegration:
    def test_ecbackend_with_liberation(self):
        from ceph_tpu.osd.ecbackend import ECBackend, ShardSet
        be = ECBackend("plugin=jerasure technique=liberation k=4 m=2 w=7",
                       "1.0", list(range(6)), ShardSet(), chunk_size=896)
        rng = np.random.default_rng(3)
        base = rng.integers(0, 256, size=5000, dtype=np.uint8)
        be.write_objects({"o": base})
        patch = rng.integers(0, 256, size=333, dtype=np.uint8)
        be.write_at("o", 700, patch)
        want = base.copy()
        want[700:1033] = patch
        np.testing.assert_array_equal(be.read_object("o"), want)
        be.cluster.stores.pop(be.acting[1])
        be.recover_shards([1], replacement_osds={1: 50})
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.deep_scrub()["inconsistent"] == []

    def test_refusal_lifted_but_bad_geometry_still_rejected(self):
        with pytest.raises(ValueError):
            factory("plugin=jerasure technique=liberation k=4 m=3 w=7")
        with pytest.raises(ValueError):
            factory("plugin=jerasure technique=liber8tion k=9 m=2")
