"""Partial-stripe write fast path (r16): GF parity-delta RMW, the
per-PG stripe journal, and append streams.

Contracts under test:
  * BIT-EXACTNESS — parity after `apply_delta` (the xor store op fed
    by the fused delta encode) is bit-identical to a full re-encode
    oracle of the final logical bytes, across RS/LRC/Clay geometries
    and both integrity modes (native host crc32c and the device
    launch), including the incremental hinfo CRCs (CRC32C
    GF(2)-linearity — no full-shard re-read ever happens);
  * REFUSAL — a degraded stripe refuses the delta path and ladders to
    the full-stripe RMW (a delta against a reconstructed pre-image
    would fold garbage into parity);
  * CRASH CONSISTENCY — SIGKILL at every stripe-journal phase
    boundary recovers (TinStore remount + `stripe_journal_replay`) to
    a state bit-exact with either the old or the new stripe, never a
    torn mix, fsck-clean;
  * APPEND — tail appends into stripe padding skip the read phase
    entirely and never re-encode previously appended bytes.
"""

import os

import numpy as np
import pytest

from ceph_tpu.ec.registry import factory
from ceph_tpu.osd import ecbackend as ecb
from ceph_tpu.osd.ecbackend import ECBackend, ShardSet, shard_cid
from ceph_tpu.osd.pgbackend import HINFO_KEY
from ceph_tpu.osd.stripe import HashInfo


def _integrity_modes(tier1_device: bool = True):
    """Both integrity modes when the native host path is built. The
    device mode duplicates ride the nightly (-m slow) except where
    `tier1_device` keeps one tier-1 representative — the 870 s tier-1
    budget is nearly full and the device path is one code path, not
    one per geometry."""
    from ceph_tpu.osd.ecbackend import _host_crc_available
    if not _host_crc_available():
        return ["device"]
    dev = pytest.param("device", marks=()) if tier1_device \
        else pytest.param("device", marks=pytest.mark.slow)
    return ["host", dev]


@pytest.fixture
def integrity(request, monkeypatch):
    """Force the RMW integrity mode: 'device' pins every CRC and
    delta encode onto the batched launches even when the native host
    path is built."""
    if request.param == "device":
        monkeypatch.setattr(ecb, "_host_crc_available", lambda: False)
    return request.param


GEOMETRIES = [
    ("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256),
    pytest.param("plugin=tpu_rs k=3 m=3 technique=cauchy_good "
                 "impl=logexp", 256, marks=pytest.mark.slow),
    ("plugin=lrc k=4 m=2 l=3 impl=bitlinear", 256),
    ("plugin=clay k=4 m=2 impl=bitlinear", 512),
    pytest.param("plugin=clay k=4 m=2 d=5 impl=ref", None,
                 marks=pytest.mark.slow),
]


def _make(profile, chunk_size):
    coder = factory(profile)
    n = coder.get_chunk_count()
    cluster = ShardSet()
    be = ECBackend(profile, "1.0", list(range(n)), cluster,
                   chunk_size=chunk_size)
    return be, cluster


def _assert_stores_match_oracle(be, name, logical):
    """Every live shard's bytes AND hinfo CRC must equal a from-
    scratch re-encode of the final logical content."""
    sl = be._shard_len(len(logical))
    dshards = be.sinfo.object_to_shards(
        np.asarray(logical, np.uint8)[None, :])
    parity = np.asarray(be.coder.encode_chunks(dshards))
    full = be._slots_from_dense(
        np.concatenate([dshards, parity], axis=1))[0]       # (n, sl)
    crcs = be._batched_hinfo_crcs(full)
    for s in range(be.n):
        st = be._store(s)
        cid = shard_cid(be.pg, s)
        np.testing.assert_array_equal(
            st.read(cid, name), full[s],
            err_msg=f"shard {s} bytes diverge from re-encode oracle")
        hinfo = HashInfo.from_bytes(st.getattr(cid, name, HINFO_KEY))
        assert hinfo.total_chunk_size == sl, f"shard {s} hinfo len"
        assert hinfo.get_chunk_hash(0) == int(crcs[s]), \
            f"shard {s}: incremental hinfo CRC != recomputed CRC"


class TestDeltaBitExact:
    @pytest.mark.parametrize("integrity",
                             _integrity_modes(tier1_device=False),
                             indirect=True)
    @pytest.mark.parametrize("profile,chunk", GEOMETRIES)
    def test_parity_after_delta_matches_reencode_oracle(
            self, profile, chunk, integrity):
        be, _ = _make(profile, chunk)
        rng = np.random.default_rng(42)
        size = be.sinfo.stripe_width * 2 + 123
        base = rng.integers(0, 256, size, np.uint8)
        be.write_objects({"o": base})
        shadow = base.copy()
        # several partial overwrites: single-column, cross-column,
        # second-stripe, and an in-padding extension
        cs = be.sinfo.chunk_size
        for off, ln in [(10, 50), (cs - 7, 30),
                        (be.sinfo.stripe_width + 5, 2 * cs - 9),
                        (size - 3, 40)]:
            patch = rng.integers(0, 256, ln, np.uint8)
            be.write_at("o", off, patch)
            if off + ln > len(shadow):
                grown = np.zeros(off + ln, np.uint8)
                grown[:len(shadow)] = shadow
                shadow = grown
            shadow[off:off + ln] = patch
            np.testing.assert_array_equal(be.read_object("o"), shadow)
        d = be.perf.dump()
        assert d["rmw_ops"] >= 4, "writes did not ride the delta path"
        assert d["rmw_full_fallbacks"] == 0
        _assert_stores_match_oracle(be, "o", shadow)
        assert be.deep_scrub()["inconsistent"] == []

    @pytest.mark.parametrize("integrity", _integrity_modes(),
                             indirect=True)
    def test_only_touched_plus_parity_shards_move(self, integrity):
        """The wire contract: a single-column overwrite transacts on
        exactly 1 data + m parity shards — untouched data shards see
        no store transaction at all."""
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(7)
        base = rng.integers(0, 256, 3000, np.uint8)
        be.write_objects({"o": base})
        before = {s: be._store(s).committed_txns for s in range(be.n)}
        patch = rng.integers(0, 256, 64, np.uint8)
        be.write_at("o", 300, patch)    # column 1, stripe 0
        touched = {s for s in range(be.n)
                   if be._store(s).committed_txns != before[s]}
        parity_slots = {be.chunk_mapping[be.k + j]
                        for j in range(be.m)}
        assert touched == {be.data_slots[1]} | parity_slots
        d = be.perf.dump()
        assert d["rmw_shard_ios"] == 1 + be.m
        assert d["rmw_ops"] == 1

    def test_delta_program_key_shared_across_instances(self):
        """The process-wide program contract: two coders with one
        geometry expose EQUAL delta keys (the r10 sharing rule — one
        compiled program per process, not per PG per daemon); a
        different geometry does not."""
        a = factory("plugin=tpu_rs k=4 m=2 impl=bitlinear")
        b = factory("plugin=tpu_rs k=4 m=2 impl=bitlinear")
        c = factory("plugin=tpu_rs k=4 m=2 impl=bitlinear "
                    "technique=cauchy_good")
        assert a.delta_program_key((1,)) == b.delta_program_key((1,))
        assert a.delta_program_key((1,)) != c.delta_program_key((1,))
        # vector codes have no static form; the generic path serves
        clay = factory("plugin=clay k=4 m=2 d=5 impl=ref")
        assert clay.delta_program_key((1,)) is None


class TestDeltaRefusal:
    def test_degraded_stripe_refuses_and_ladders_to_full(self):
        be, cluster = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear",
                            256)
        rng = np.random.default_rng(9)
        base = rng.integers(0, 256, 3000, np.uint8)
        be.write_objects({"o": base})
        dead_osd = be.acting[1]
        cluster.stores.pop(dead_osd)
        patch = rng.integers(0, 256, 64, np.uint8)
        be.write_at("o", 10, patch, dead_osds={dead_osd})
        want = base.copy()
        want[10:74] = patch
        np.testing.assert_array_equal(
            be.read_object("o", dead_osds={dead_osd}), want)
        d = be.perf.dump()
        assert d["rmw_full_fallbacks"] >= 1
        assert d["rmw_ops"] == 0, \
            "a degraded stripe must never take the delta path"

    def test_stale_shard_refuses_delta(self):
        """A revived-but-behind shard (cursor below the object's
        version) is as unsafe a delta base as a dead one."""
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(10)
        be.write_objects({"o": rng.integers(0, 256, 2000, np.uint8)})
        be.shard_applied[2] = 0          # simulate a lagging shard
        be.write_at("o", 5, rng.integers(0, 256, 40, np.uint8))
        d = be.perf.dump()
        assert d["rmw_ops"] == 0 and d["rmw_full_fallbacks"] >= 1

    def test_overlapping_writes_in_one_wave_refuse(self):
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(11)
        base = rng.integers(0, 256, 2000, np.uint8)
        be.write_objects({"o": base})
        a = rng.integers(0, 256, 50, np.uint8)
        b = rng.integers(0, 256, 50, np.uint8)
        be.write_ranges([("o", 100, a), ("o", 120, b)])
        want = base.copy()
        want[100:150] = a
        want[120:170] = b                # later op wins the overlap
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.perf.dump()["rmw_ops"] == 0

    def test_clay_length_change_refuses(self):
        """Vector codes couple bytes across the chunk: an extension
        that changes shard length must re-encode, not delta."""
        be, _ = _make("plugin=clay k=2 m=2 impl=ref", 512)
        rng = np.random.default_rng(12)
        sw = be.sinfo.stripe_width
        base = rng.integers(0, 256, sw, np.uint8)
        be.write_objects({"o": base})
        tail = rng.integers(0, 256, 300, np.uint8)
        be.write_at("o", sw, tail)       # grows the shard
        want = np.concatenate([base, tail])
        np.testing.assert_array_equal(be.read_object("o"), want)
        assert be.perf.dump()["rmw_ops"] == 0
        assert be.deep_scrub()["inconsistent"] == []


class TestAppendStreams:
    @pytest.mark.parametrize("integrity",
                             _integrity_modes(tier1_device=False),
                             indirect=True)
    def test_appends_skip_preread_and_reencode(self, integrity):
        """The append-optimized layout: successive tail appends into
        the padded stripe read NOTHING (the pre-image is zeros by the
        layout rule) and never re-encode previously appended bytes —
        no full-stripe encode launches after the create."""
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(13)
        first = rng.integers(0, 256, 100, np.uint8)
        be.write_objects({"log": first})
        d0 = be.perf.dump()
        shadow = first
        for _ in range(6):
            chunk = rng.integers(0, 256,
                                 int(rng.integers(30, 200)), np.uint8)
            be.append_objects({"log": chunk})
            shadow = np.concatenate([shadow, chunk])
        d1 = be.perf.dump()
        assert d1["rmw_append_fast"] - d0["rmw_append_fast"] == 6
        assert d1["rmw_preread_bytes"] == d0["rmw_preread_bytes"], \
            "appends into padding must not read a pre-image"
        # no full-stripe encode after the create: the tail stripe is
        # never re-encoded, only delta-folded
        for key in ("fused_write_launches", "host_encode_launches",
                    "encode_launches", "write_wire_bytes"):
            assert d1[key] == d0[key], key
        np.testing.assert_array_equal(be.read_object("log"), shadow)
        _assert_stores_match_oracle(be, "log", shadow)
        assert be.deep_scrub()["inconsistent"] == []


class _SimulatedKill(Exception):
    pass


def _tin_cluster(root):
    from ceph_tpu.osd.tinstore import TinStore
    return ShardSet(store_factory=lambda osd: TinStore(
        os.path.join(root, f"osd.{osd}")))


def _rebuild(cluster, meta_src):
    """A post-crash primary: fresh backend view over the remounted
    stores, carrying the persisted-metadata analog (sizes/versions/
    log/cursors survive on the wire tier's meta plane)."""
    be2 = ECBackend("plugin=tpu_rs k=4 m=2 impl=bitlinear", "1.0",
                    list(range(6)), cluster, chunk_size=256,
                    ensure_collections=False)
    be2.object_sizes = dict(meta_src.object_sizes)
    be2.object_versions = dict(meta_src.object_versions)
    be2.pg_log = meta_src.pg_log
    be2.shard_applied = list(meta_src.shard_applied)
    return be2


PHASES = ["before_prepare", "mid_prepare", "after_prepare",
          "mid_apply", "after_apply"]


class TestStripeJournalCrashMatrix:
    @pytest.mark.parametrize("phase", PHASES)
    def test_sigkill_at_phase_boundary_never_tears(self, phase,
                                                   tmp_path):
        """Kill the whole store set at each journal phase boundary;
        after remount + replay the stripe is bit-exact with either
        the OLD or the NEW content (prepare incomplete -> old;
        prepare complete -> new), hinfo verifies, deep scrub is
        clean, and offline fsck finds nothing."""
        from ceph_tpu.osd.tinstore import TinStore
        root = str(tmp_path)
        cluster = _tin_cluster(root)
        be = ECBackend("plugin=tpu_rs k=4 m=2 impl=bitlinear", "1.0",
                       list(range(6)), cluster, chunk_size=256)
        rng = np.random.default_rng(21)
        base = rng.integers(0, 256, 3000, np.uint8)
        be.write_objects({"o": base})
        patch = rng.integers(0, 256, 100, np.uint8)
        new = base.copy()
        new[500:600] = patch

        def hook(p):
            if p == phase:
                for st in cluster.stores.values():
                    st.crash()           # SIGKILL semantics: RAM gone
                raise _SimulatedKill(p)
        be._rmw_crash_hook = hook
        with pytest.raises(_SimulatedKill):
            be.write_at("o", 500, patch)
        for st in cluster.stores.values():
            st.remount()
        be2 = _rebuild(cluster, be)
        rep = be2.stripe_journal_replay()
        got = be2.read_object("o")
        if np.array_equal(got, new):
            state = "new"
        elif np.array_equal(got, base):
            state = "old"
        else:
            state = "torn"
        assert state != "torn", f"phase {phase}: torn stripe"
        # prepare-incomplete phases MUST resolve old; post-prepare
        # phases MUST roll forward to new
        want = {"before_prepare": "old", "mid_prepare": "old",
                "after_prepare": "new", "mid_apply": "new",
                "after_apply": "new"}[phase]
        assert state == want, (phase, state, rep)
        oracle = new if state == "new" else base
        _assert_stores_match_oracle(be2, "o", oracle)
        assert be2.deep_scrub()["inconsistent"] == []
        # replay is idempotent: a second crash-during-replay rerun
        # must be a no-op
        rep2 = be2.stripe_journal_replay()
        assert rep2["entries"] == 0
        np.testing.assert_array_equal(be2.read_object("o"), oracle)
        for osd in range(6):
            path = os.path.join(root, f"osd.{osd}")
            fr = TinStore.fsck(path)
            assert not (fr["errors"] or fr["extent_errors"]
                        or fr["bad_objects"]), (osd, fr)

    def test_replay_seq_reanchors_past_crash(self, tmp_path):
        """New RMWs after a replay must not reuse journal sequence
        numbers an old watermark already covers (a reused seq would
        fake the roll-forward evidence)."""
        cluster = _tin_cluster(str(tmp_path))
        be = ECBackend("plugin=tpu_rs k=4 m=2 impl=bitlinear", "1.0",
                       list(range(6)), cluster, chunk_size=256)
        rng = np.random.default_rng(22)
        base = rng.integers(0, 256, 2000, np.uint8)
        be.write_objects({"o": base})
        be.write_at("o", 10, rng.integers(0, 256, 40, np.uint8))
        be.write_at("o", 90, rng.integers(0, 256, 40, np.uint8))
        high = be._rmw_seq
        be2 = _rebuild(cluster, be)
        be2.stripe_journal_replay()
        assert be2._rmw_seq >= high
        patch = rng.integers(0, 256, 40, np.uint8)
        be2.write_at("o", 200, patch)    # must journal cleanly
        assert be2.deep_scrub()["inconsistent"] == []


class TestPrepareFetchCoalescing:
    """r17 follow-up: the delta prepare's 1+m tiny per-shard getattrs
    and per-span pre-reads coalesce into ONE combined fetch wave per
    delta group — one frame per participant shard, however many jobs
    and spans the group carries."""

    def test_one_wave_one_frame_per_participant(self):
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(31)
        base = rng.integers(0, 256, 3000, np.uint8)
        be.write_objects({"a": base, "b": base[::-1].copy()})
        w0, f0 = (be.perf.get("rmw_fetch_waves"),
                  be.perf.get("rmw_fetch_frames"))
        # two jobs, same (touched, window) shape -> ONE group, ONE
        # wave; participants = 1 data + m parity = 3 shards
        pa = rng.integers(0, 256, 40, np.uint8)
        pb = rng.integers(0, 256, 40, np.uint8)
        be.write_ranges([("a", 10, pa), ("b", 10, pb)])
        assert be.perf.get("rmw_fetch_waves") - w0 == 1
        assert be.perf.get("rmw_fetch_frames") - f0 == 1 + be.m
        want = base.copy()
        want[10:50] = pa
        _assert_stores_match_oracle(be, "a", want)

    def test_growth_wave_touches_every_shard_once(self):
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(32)
        base = rng.integers(0, 256, 900, np.uint8)
        be.write_objects({"g": base})
        f0 = be.perf.get("rmw_fetch_frames")
        # growth (nsl != osl: the append lands in the NEXT stripe, so
        # every shard zero-extends): all n participate, one frame each
        be.write_at("g", 1100, rng.integers(0, 256, 30, np.uint8))
        assert be.perf.get("rmw_fetch_frames") - f0 == be.n

    def test_wire_tier_prefetch_round_trips(self):
        """On the wire tier the wave really is pipelined RemoteStore
        frames: rmw_fetch store ops serve it, and the overwrite's
        bytes land bit-exact."""
        from ceph_tpu.osd.standalone import StandaloneCluster
        c = StandaloneCluster(n_osds=5,
                              profile="plugin=tpu_rs k=2 m=1 "
                                      "impl=bitlinear",
                              pg_num=2)
        try:
            cl = c.client()
            rng = np.random.default_rng(33)
            base = rng.integers(0, 256, 1500, np.uint8).tobytes()
            cl.write({"w": base})
            def waves():
                return sum(d.ec_perf.get("rmw_fetch_waves")
                           for d in c.osds.values()
                           if not d._stop.is_set())
            w0 = waves()
            patch = rng.integers(0, 256, 64, np.uint8).tobytes()
            cl.write_at("w", 100, patch)
            assert waves() > w0
            want = bytearray(base)
            want[100:164] = patch
            assert cl.read("w") == bytes(want)
        finally:
            c.shutdown()


class TestJournalAwareDeepScrub:
    """r17 follow-up: deep scrub audits pending __stripe_journal__
    intents (seq/version/geometry consistency against the applied
    watermark) instead of skipping the collection."""

    def test_clean_pg_reports_empty_journal_blocks(self):
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(41)
        be.write_objects({"o": rng.integers(0, 256, 2000, np.uint8)})
        be.write_at("o", 10, rng.integers(0, 256, 40, np.uint8))
        rep = be.deep_scrub()
        assert rep["inconsistent"] == []
        assert rep["journal_bad"] == []
        assert rep["journal_pending"] == 0     # applied + dropped

    def test_corrupt_intent_detected(self):
        from ceph_tpu.osd.memstore import Transaction
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(42)
        be.write_objects({"o": rng.integers(0, 256, 2000, np.uint8)})
        s = 0
        cid = shard_cid(be.pg, s)
        # (1) garbage bytes under a journal key
        be._store(s).queue_transaction(Transaction().omap_set(
            cid, be.JOURNAL_OBJ, {be._jkey(99): b"\x07garbage"}))
        rep = be.deep_scrub()
        assert any("undecodable" in why for sl, why in
                   rep["journal_bad"] if sl == s), rep
        assert rep["inconsistent"] == []       # journal findings stay
        #                                        out of auto-repair's
        #                                        rebuild list
        # (2) a decodable intent whose seq sits below the watermark
        be._store(s).queue_transaction(Transaction().omap_set(
            cid, be.JOURNAL_OBJ,
            {be._J_APPLIED: __import__("struct").pack("<Q", 50),
             be._jkey(7): be._encode_jentry(
                 7, "o", s, [s], 2000, 500, 500, 0, b"", 0, 1)}))
        rep2 = be.deep_scrub()
        assert any("watermark" in why for sl, why in
                   rep2["journal_bad"] if sl == s), rep2
        # (3) a geometry overrun: delta runs past the shard length
        be._store(s).queue_transaction(Transaction().omap_set(
            cid, be.JOURNAL_OBJ,
            {be._jkey(60): be._encode_jentry(
                60, "o", s, [s], 2000, 500, 500, 400,
                b"\x00" * 200, 0, 999)}))
        rep3 = be.deep_scrub()
        assert any("overruns" in why for sl, why in
                   rep3["journal_bad"] if sl == s), rep3

    def test_pending_intent_counts_not_flags(self):
        """A legitimate in-flight intent (prepare done, apply not) is
        journal_pending — crash-recovery state, never 'bad'."""
        be, _ = _make("plugin=tpu_rs k=4 m=2 impl=bitlinear", 256)
        rng = np.random.default_rng(43)
        be.write_objects({"o": rng.integers(0, 256, 2000, np.uint8)})

        class _Stop(Exception):
            pass

        def hook(p):
            if p == "after_prepare":
                raise _Stop()
        be._rmw_crash_hook = hook
        with pytest.raises(_Stop):
            be.write_at("o", 10, rng.integers(0, 256, 40, np.uint8))
        be._rmw_crash_hook = None
        rep = be.deep_scrub()
        assert rep["journal_bad"] == []
        assert rep["journal_pending"] > 0
