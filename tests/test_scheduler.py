"""Throttle + mClock scheduler tests (ref behaviors: src/common/
Throttle.cc gtests; mClock QoS properties — reservation floor, weight
sharing, limit ceiling — per the dmclock design the reference wraps)."""

import threading
import time

import pytest

from ceph_tpu.osd.scheduler import (ClientProfile, MClockScheduler)
from ceph_tpu.utils.throttle import Throttle


class TestThrottle:
    def test_basic_get_put(self):
        t = Throttle("t", 10)
        assert t.get(4)
        assert t.get(6)
        assert t.get_current() == 10
        assert not t.get_or_fail(1)
        assert t.put(6) == 4
        assert t.get_or_fail(1)

    def test_zero_max_disables(self):
        t = Throttle("t", 0)
        for _ in range(100):
            assert t.get_or_fail(1000)
        assert t.get(10**9)

    def test_oversized_request_admitted_alone(self):
        t = Throttle("t", 5)
        assert t.get(3)
        got = []

        def worker():
            got.append(t.get(8, timeout=5.0))
        th = threading.Thread(target=worker)
        th.start()
        time.sleep(0.05)
        assert not got          # blocked: 3 held, 8 > max
        t.put(3)                # drains to 0 -> oversized admitted
        th.join(5.0)
        assert got == [True]
        assert t.get_current() == 8

    def test_fifo_no_starvation(self):
        t = Throttle("t", 10)
        assert t.get(9)
        order = []

        def big():
            t.get(8, timeout=5.0)
            order.append("big")

        def small():
            t.get(1, timeout=5.0)
            order.append("small")
        b = threading.Thread(target=big)
        b.start()
        time.sleep(0.05)
        s = threading.Thread(target=small)
        s.start()
        time.sleep(0.05)
        # small would fit (9+1<=10) but big is ahead in FIFO
        assert order == []
        t.put(9)
        b.join(5.0)
        s.join(5.0)
        assert order == ["big", "small"]

    def test_get_timeout(self):
        t = Throttle("t", 2)
        assert t.get(2)
        t0 = time.perf_counter()
        assert not t.get(1, timeout=0.1)
        assert time.perf_counter() - t0 < 2.0
        t.put(2)
        assert t.get(1)  # waiter list cleaned up after timeout

    def test_put_more_than_held_raises(self):
        t = Throttle("t", 5)
        t.get(2)
        with pytest.raises(ValueError):
            t.put(3)

    def test_reset_max_wakes(self):
        t = Throttle("t", 2)
        t.get(2)
        got = []

        def worker():
            got.append(t.get(2, timeout=5.0))
        th = threading.Thread(target=worker)
        th.start()
        time.sleep(0.05)
        t.reset_max(10)
        th.join(5.0)
        assert got == [True]


def run_sim(sched: MClockScheduler, feeders: dict[str, int],
            seconds: float = 2.0, capacity_per_s: float = 1000.0,
            dt: float = 0.001) -> dict[str, int]:
    """Keep every class saturated with `feeders[cls]` queued ops; pump
    at `capacity_per_s`; count ops served per class."""
    served = {c: 0 for c in feeders}
    now = 0.0
    budget_per_step = capacity_per_s * dt
    carry = 0.0
    while now < seconds:
        for cls, depth in feeders.items():
            # top the queue back up (saturation)
            backlog = sum(1 for q in [sched._classes[cls]]
                          for _ in q.items)
            for _ in range(depth - backlog):
                sched.enqueue(cls, object())
        carry += budget_per_step
        while carry >= 1.0:
            got = sched.dequeue(now)
            if got is None:
                break
            served[got[0]] += 1
            carry -= 1.0
        now += dt
    return served


class TestMClock:
    def test_weight_proportional_share(self):
        s = MClockScheduler({
            "a": ClientProfile(weight=3.0),
            "b": ClientProfile(weight=1.0),
        })
        served = run_sim(s, {"a": 10, "b": 10}, seconds=1.0,
                         capacity_per_s=400.0)
        ratio = served["a"] / max(1, served["b"])
        assert 2.4 < ratio < 3.6, served

    def test_reservation_floor_under_pressure(self):
        # low-weight class with a 100/s reservation must still get
        # ~100/s although the heavy class would otherwise take ~all
        s = MClockScheduler({
            "heavy": ClientProfile(weight=100.0),
            "floor": ClientProfile(reservation=100.0, weight=0.001),
        })
        served = run_sim(s, {"heavy": 20, "floor": 20}, seconds=2.0,
                         capacity_per_s=500.0)
        assert served["floor"] >= 190, served   # ~100/s over 2s
        assert served["heavy"] >= 700, served   # rest of capacity

    def test_limit_ceiling(self):
        s = MClockScheduler({
            "capped": ClientProfile(weight=10.0, limit=50.0),
        })
        served = run_sim(s, {"capped": 50}, seconds=2.0,
                         capacity_per_s=1000.0)
        assert served["capped"] <= 110, served  # ~50/s over 2s

    def test_spare_capacity_goes_to_unlimited(self):
        s = MClockScheduler({
            "capped": ClientProfile(weight=10.0, limit=50.0),
            "open": ClientProfile(weight=1.0),
        })
        served = run_sim(s, {"capped": 50, "open": 50}, seconds=1.0,
                         capacity_per_s=1000.0)
        assert served["capped"] <= 60, served
        assert served["open"] >= 900, served

    def test_idle_class_does_not_bank_credit(self):
        s = MClockScheduler({
            "capped": ClientProfile(weight=1.0, limit=100.0),
        })
        # idle from t=0..10, then saturate for 0.5s: must get ~50 ops,
        # not 10s * 100/s of banked burst
        for _ in range(2000):
            s.enqueue("capped", object())
        served = 0
        now = 10.0
        while now < 10.5:
            while s.dequeue(now) is not None:
                served += 1
            now += 0.001
        assert served <= 60, served

    def test_fifo_within_class(self):
        s = MClockScheduler({"c": ClientProfile(weight=1.0)})
        for i in range(5):
            s.enqueue("c", i)
        got = [s.dequeue(float(i))[1] for i in range(5)]
        assert got == [0, 1, 2, 3, 4]

    def test_unknown_class_raises(self):
        s = MClockScheduler()
        with pytest.raises(KeyError):
            s.enqueue("nope", object())

    def test_default_profiles_recovery_vs_client(self):
        s = MClockScheduler()  # DEFAULT_PROFILES
        served = run_sim(s, {"client": 50, "background_recovery": 50},
                         seconds=1.0, capacity_per_s=400.0)
        # recovery makes progress (reservation floor) but clients
        # dominate (weight 10 vs 5, recovery limited to 100/s)
        assert served["background_recovery"] >= 25
        assert served["background_recovery"] <= 120
        assert served["client"] > served["background_recovery"]


class TestReviewRegressions:
    def test_timeout_head_passes_baton(self):
        # head waiter timing out must wake the next waiter if it fits
        t = Throttle("t", 10)
        assert t.get(8)
        got = []

        def big():
            got.append(("big", t.get(5, timeout=0.15)))

        def small():
            got.append(("small", t.get(2, timeout=5.0)))
        b = threading.Thread(target=big)
        b.start()
        time.sleep(0.05)
        s = threading.Thread(target=small)
        s.start()
        b.join(5.0)   # big times out (8+5>10)
        s.join(5.0)   # small (8+2<=10) must be woken by the departure
        assert ("big", False) in got
        assert ("small", True) in got

    def test_remove_if_purges_cancelled_ops(self):
        s = MClockScheduler({"c": ClientProfile(weight=1.0, limit=10.0)})
        for i in range(20):
            s.enqueue("c", ("pg1", i))
        for i in range(3):
            s.enqueue("c", ("pg2", i))
        assert s.remove_if("c", lambda op: op[0] == "pg1") == 20
        assert len(s) == 3
        got = [s.dequeue(100.0 + i) for i in range(3)]
        assert [g[1][0] for g in got] == ["pg2"] * 3
        assert s.dequeue(200.0) is None


class TestRound10Additions:
    def test_next_eligible_limit_bound(self):
        s = MClockScheduler({
            "capped": ClientProfile(weight=1.0, limit=10.0)})
        for _ in range(3):
            s.enqueue("capped", object())
        assert s.next_eligible(0.0) == 0.0      # head servable now
        assert s.dequeue(0.0) is not None
        # head now spaced by 1/limit: eligible ~0.1s out, not "poll me
        # every tick"
        t = s.next_eligible(0.0)
        assert t is not None and 0.05 < t <= 0.11
        assert s.next_eligible(1.0) == 1.0      # past the spacing
        s.dequeue(1.0)
        s.dequeue(2.0)
        assert s.next_eligible(3.0) is None     # empty queue

    def test_dump_counts_grants(self):
        s = MClockScheduler()
        for i in range(4):
            s.enqueue("client", i, cost=2.0)
        s.enqueue("background_recovery", "r", cost=5.0)
        for t in range(3):
            s.dequeue(float(t))
        d = s.dump()
        assert sum(c["served"] for c in d.values()) == 3
        assert sum(c["queued"] for c in d.values()) == 2
        assert d["client"]["profile"]["weight"] == 10.0
        served_cost = sum(c["served_cost"] for c in d.values())
        assert served_cost > 0


class TestPerTenantClasses:
    """Round-11: dynamic per-tenant (ρ, w, λ) classes keyed by client
    entity (ensure_class + the osd_mclock_scheduler_tenant_* config
    grammar) — one heavy tenant must not starve the rest."""

    def test_parse_profile_and_table(self):
        from ceph_tpu.osd.scheduler import (parse_profile,
                                            parse_profile_table)
        p = parse_profile(" 50, 10 , 0 ")
        assert (p.reservation, p.weight, p.limit) == (50.0, 10.0, 0.0)
        table = parse_profile_table(
            "client.a=1,2,3;client.b=0,5,0;")
        assert set(table) == {"client.a", "client.b"}
        assert table["client.a"].limit == 3.0
        with pytest.raises(ValueError):
            parse_profile("1,2")          # not three fields
        with pytest.raises(ValueError):
            parse_profile_table("justanentity")   # no '='
        with pytest.raises(ValueError):
            parse_profile("5,1,3")        # reservation > limit

    def test_ensure_class_creates_then_retunes(self):
        s = MClockScheduler()
        s.ensure_class("tenant:a", ClientProfile(weight=2.0))
        s.enqueue("tenant:a", "op")
        assert s.dequeue(0.0) == ("tenant:a", "op")
        # retune in place: profile changes, queue/order survive
        s.enqueue("tenant:a", "op2")
        s.ensure_class("tenant:a", ClientProfile(weight=9.0))
        assert s.dump()["tenant:a"]["profile"]["weight"] == 9.0
        assert s.dequeue(1.0) == ("tenant:a", "op2")
        # idempotent for an unchanged profile
        s.ensure_class("tenant:a", ClientProfile(weight=9.0))
        assert "tenant:a" in s.class_names()

    def test_tenant_weight_split_under_saturation(self):
        # two tenants sharing spare capacity 4:1 by weight — the
        # "heavy tenant cannot starve the rest" property in its
        # simplest measurable form
        s = MClockScheduler({
            "tenant:heavy": ClientProfile(weight=4.0),
            "tenant:light": ClientProfile(weight=1.0),
        })
        served = run_sim(s, {"tenant:heavy": 10, "tenant:light": 10},
                         seconds=1.0, capacity_per_s=500.0)
        ratio = served["tenant:heavy"] / max(1, served["tenant:light"])
        assert 3.2 < ratio < 4.8, served

    def test_tenant_limit_caps_hedge_storms(self):
        # a tenant flooding duplicates under a λ cap cannot exceed its
        # ceiling; an unlimited tenant soaks the rest
        s = MClockScheduler({
            "tenant:storm": ClientProfile(weight=10.0, limit=50.0),
            "tenant:calm": ClientProfile(weight=1.0),
        })
        served = run_sim(s, {"tenant:storm": 50, "tenant:calm": 50},
                         seconds=1.0, capacity_per_s=1000.0)
        assert served["tenant:storm"] <= 60, served
        assert served["tenant:calm"] >= 900, served
