"""PGLog + delta rejoin: a revived OSD replays only missed mutations
(ref: src/osd/PGLog.{h,cc} log-based recovery vs backfill; the r01
cluster refused revive after mark-down — VERDICT item 6)."""

import numpy as np
import pytest

from ceph_tpu.osd.cluster import SimCluster
from cluster_helpers import corpus, make_cluster
from ceph_tpu.osd.pglog import PGLog


class TestPGLogUnit:
    def test_append_and_missing(self):
        log = PGLog()
        assert log.missing_since(0) == []
        v1 = log.append("a")
        v2 = log.append("b")
        log.append("a")
        assert v2 > v1
        assert log.missing_since(0) == ["a", "b"]  # dedup, oldest-first
        assert log.missing_since(v2) == ["a"]
        assert log.missing_since(log.head) == []

    def test_trim_signals_backfill(self):
        log = PGLog(max_entries=4)
        for i in range(10):
            log.append(f"o{i}")
        assert len(log) == 4
        assert log.missing_since(0) is None          # predates the log
        assert log.missing_since(log.tail - 1) is None
        assert log.missing_since(log.tail) == ["o6", "o7", "o8", "o9"]

    def test_bad_max(self):
        with pytest.raises(ValueError):
            PGLog(max_entries=0)


class TestDeltaRejoin:
    def test_revive_after_down_replays_missed_writes(self):
        c = make_cluster()
        objs = corpus()
        c.write(objs)
        victim = 5
        c.kill_osd(victim)
        c.tick(30.0)                       # grace expires -> marked down
        assert not c.osdmap.osd_up[victim]
        # mutations while down: overwrites + brand-new objects
        rng = np.random.default_rng(9)
        for name in list(objs)[:8]:
            objs[name] = rng.integers(0, 256, 700, np.uint8)
        objs.update(corpus(n=6, seed=10, prefix="late"))
        c.write(objs)
        c.revive_osd(victim)               # delta replay, not refusal
        assert c.osdmap.osd_up[victim]
        assert victim not in c.down_since
        assert c.perf.get("log_replayed_objects") > 0
        assert c.perf.get("revive_full_rebuilds") == 0
        assert c.verify_all(objs) == len(objs)
        # the revived shard itself must be consistent: read with every
        # OTHER candidate combination by deep-scrubbing each PG
        for be in c.pgs.values():
            assert be.deep_scrub()["inconsistent"] == []

    def test_revive_with_nothing_missed_is_free(self):
        c = make_cluster()
        objs = corpus()
        c.write(objs)
        c.kill_osd(3)
        c.tick(30.0)
        c.revive_osd(3)
        assert c.perf.get("log_replayed_objects") == 0
        assert c.verify_all(objs) == len(objs)

    def test_trimmed_log_forces_full_rebuild(self):
        c = make_cluster()
        objs = corpus(n=8)
        c.write(objs)
        c.kill_osd(2)
        c.tick(30.0)
        for be in c.pgs.values():          # shrink logs under the rug
            be.pg_log.max_entries = 2
        # enough churn to trim every PG's log past the dead cursor
        rng = np.random.default_rng(4)
        for r in range(4):
            for name in objs:
                objs[name] = rng.integers(0, 256, 700, np.uint8)
            c.write(objs)
        c.revive_osd(2)
        assert c.perf.get("revive_full_rebuilds") > 0
        assert c.verify_all(objs) == len(objs)
        for be in c.pgs.values():
            assert be.deep_scrub()["inconsistent"] == []

    def test_degraded_write_skips_dead_store(self):
        c = make_cluster()
        objs = corpus(n=6)
        c.write(objs)
        victim = 1
        c.kill_osd(victim)                 # within grace, not marked down
        before = {ps: dict(c.cluster.osd(victim).data)
                  for ps in range(1)
                  if victim in c.cluster.stores} \
            if hasattr(c.cluster.osd(victim), "data") else None
        rng = np.random.default_rng(7)
        objs["obj-0"] = rng.integers(0, 256, 700, np.uint8)
        c.write({"obj-0": objs["obj-0"]})
        # the dead store held its pre-kill shard; reads avoid it
        assert c.verify_all(objs) == len(objs)
        c.revive_osd(victim)
        assert c.verify_all(objs) == len(objs)
        for be in c.pgs.values():
            assert be.deep_scrub()["inconsistent"] == []

    def test_deferred_replay_resolves_when_peers_return(self):
        # kill two OSDs of ONE PG's acting set (k=4 m=2: 4 live = k,
        # writes still allowed), mutate, then revive one at a time —
        # the first revive may defer some PG's catch-up until the
        # second returns; nothing wedges and no stale byte is served
        c = make_cluster()
        objs = corpus(n=20)
        c.write(objs)
        acting = c.pgs[0].acting
        v1, v2 = acting[0], acting[1]
        c.kill_osd(v1)
        c.kill_osd(v2)
        c.tick(30.0)
        rng = np.random.default_rng(3)
        for name in objs:
            objs[name] = rng.integers(0, 256, 700, np.uint8)
        c.write(objs)
        assert c.verify_all(objs) == len(objs)   # degraded reads OK
        c.revive_osd(v1)
        assert c.verify_all(objs) == len(objs)   # stale shards unused
        c.revive_osd(v2)
        assert c.verify_all(objs) == len(objs)
        # after both rejoin every shard is caught up
        for be in c.pgs.values():
            assert all(a == be.pg_log.head for a in be.shard_applied)
            assert be.deep_scrub()["inconsistent"] == []

    def test_write_refused_below_min_size(self):
        c = make_cluster()
        objs = corpus(n=12)
        c.write(objs)
        acting = c.pgs[0].acting
        for o in acting[:3]:                     # 3 dead > m=2
            c.kill_osd(o)
        bad = None
        rng = np.random.default_rng(5)
        # find an object living in pg 0 and try to overwrite it
        for name in objs:
            if c.locate(name) == 0:
                bad = name
                break
        assert bad is not None
        with pytest.raises(ValueError, match="min_size"):
            c.write({bad: rng.integers(0, 256, 700, np.uint8)})

    def test_thrash_kill_write_revive_cycles(self):
        c = make_cluster(down_out_interval=600.0)
        rng = np.random.default_rng(123)
        objs = corpus(n=30, seed=1)
        c.write(objs)
        for cycle in range(4):
            victim = int(rng.integers(0, 12))
            c.kill_osd(victim)
            c.tick(30.0)                   # marked down
            for _ in range(3):             # writes while down
                name = f"obj-{int(rng.integers(0, 30))}"
                objs[name] = rng.integers(0, 256, 700, np.uint8)
                c.write({name: objs[name]})
            objs[f"cycle-{cycle}"] = rng.integers(0, 256, 700, np.uint8)
            c.write({f"cycle-{cycle}": objs[f"cycle-{cycle}"]})
            c.revive_osd(victim)
            c.tick(10.0)
            assert c.verify_all(objs) == len(objs)
        assert c.perf.get("log_replayed_objects") > 0
        h = c.health()
        assert h["pgs_degraded"] == 0
        for be in c.pgs.values():
            assert be.deep_scrub()["inconsistent"] == []


class TestDivergentNames:
    """PGLog::merge_log's divergent-entry classification (r4 verdict
    item 5)."""

    def _log(self, entries, head=None, tail=0):
        from ceph_tpu.osd.pglog import PGLog
        lg = PGLog()
        for _, name in entries:
            lg.append(name)
        # rewrite versions to match the given entries exactly
        lg._entries.clear()
        for v, name in entries:
            lg._entries.append((v, name))
        lg.head = head if head is not None else max(
            (v for v, _ in entries), default=0)
        lg.tail = tail
        return lg

    def test_entries_past_auth_head_are_divergent(self):
        from ceph_tpu.osd.pglog import divergent_names
        auth = self._log([(1, "a"), (2, "b")])
        local = self._log([(1, "a"), (2, "b"), (3, "ghost"),
                           (4, "ghost2")])
        assert sorted(divergent_names(local, auth)) == \
            ["ghost", "ghost2"]

    def test_conflicting_version_is_divergent(self):
        from ceph_tpu.osd.pglog import divergent_names
        auth = self._log([(1, "a"), (2, "x"), (3, "y")])
        local = self._log([(1, "a"), (2, "b")])  # v2 names differ
        assert divergent_names(local, auth) == ["b"]

    def test_agreeing_histories_have_no_divergence(self):
        from ceph_tpu.osd.pglog import divergent_names
        auth = self._log([(1, "a"), (2, "b"), (3, "c")])
        local = self._log([(1, "a"), (2, "b")])  # merely behind
        assert divergent_names(local, auth) == []

    def test_trimmed_window_assumed_converged(self):
        from ceph_tpu.osd.pglog import divergent_names
        auth = self._log([(5, "e"), (6, "f")], head=6, tail=4)
        local = self._log([(3, "old"), (5, "e")])  # v3 predates tail
        assert divergent_names(local, auth) == []

    def test_share_history(self):
        from ceph_tpu.osd.pglog import share_history
        # stale tail: agreement on early entries
        auth = self._log([(1, "a"), (2, "b"), (3, "c")])
        local = self._log([(1, "a"), (2, "b"), (4, "ghost")], head=4)
        assert share_history(local, auth)
        # interval discontinuity: no agreement anywhere
        virgin = self._log([(1, "post-outage")])
        old = self._log([(1, "x"), (2, "y"), (3, "z")])
        assert not share_history(old, virgin)
        # local predates auth's trimmed tail: unverifiable => shared
        trimmed = self._log([(9, "n")], head=9, tail=8)
        ancient = self._log([(2, "m")], head=2)
        assert share_history(ancient, trimmed)
        # empty local always shares
        assert share_history(self._log([]), auth)
