"""Repair policy plane (r17) — DownClock classification, lazy repair
deferral/cancellation, risk-ordered burst recovery, and per-domain
repair budgets.

Unit tests drive the policy objects in VIRTUAL time (now is a
parameter everywhere, the scheduler discipline), so windows expire
instantly and nothing here sleeps. The live wire-tier cells (slow:
one extra cluster boot each; the tier-1 live representative is
test_thrash.py::test_thrash_transient_smoke) prove the payoff
end-to-end: a within-window revive moves ZERO repair bytes, and the
m-1 override beats an hour-long delay."""

import pytest

from ceph_tpu.osd.repairpolicy import (DownClock, RepairPolicy,
                                       exposure_units, order_plans,
                                       plan_helper_cost, risk_key)
from ceph_tpu.osd.scheduler import DomainBudgets, TokenBucket
from ceph_tpu.utils.config import Config

UP = [True] * 6


def down(*osds):
    return [i not in osds for i in range(6)]


def make_policy(delay=10.0, **opts):
    cfg = Config()
    cfg.set("osd_repair_delay", delay)
    for k, v in opts.items():
        cfg.set(k, v)
    p = RepairPolicy(config=cfg)
    p.observe_map(UP, now=0.0)      # baseline: everyone up
    return p, cfg


# -- DownClock ----------------------------------------------------------------

def test_downclock_transitions_and_flapping():
    ck = DownClock()
    assert ck.state == DownClock.UP
    # suspicion is reversible and never starts a deferral window
    ck.mark_suspect()
    assert ck.state == DownClock.SUSPECT
    ck.clear_suspect()
    assert ck.state == DownClock.UP
    # down -> deferred; the delay elapsing confirms
    ck.mark_down(now=100.0)
    assert ck.state == DownClock.DOWN_DEFERRED
    assert not ck.maybe_confirm_elapsed(10.0, now=105.0)
    assert ck.maybe_confirm_elapsed(10.0, now=110.0)
    assert ck.state == DownClock.DOWN_CONFIRMED
    assert ck.confirmed_reason == "delay_elapsed"
    # revive returns to up; a short dwell counts a FLAP
    ck.mark_up(now=111.0, delay=10.0)
    assert ck.state == DownClock.UP and ck.flaps == 0   # dwell 11 > 10
    for i in range(3):                                   # flapping
        ck.mark_down(now=200.0 + i)
        ck.mark_up(now=200.5 + i, delay=10.0)
    assert ck.flaps == 3
    assert ck.state == DownClock.UP
    # a second mark_down while already down is a no-op (stamp kept)
    ck.mark_down(now=300.0)
    ck.mark_down(now=305.0)
    assert ck.down_since == 300.0


def test_downclock_confirm_only_from_deferred():
    ck = DownClock()
    ck.confirm("m1_override")             # up: nothing to confirm
    assert ck.state == DownClock.UP
    ck.mark_down(now=1.0)
    ck.confirm("m1_override")
    assert ck.state == DownClock.DOWN_CONFIRMED
    assert ck.confirmed_reason == "m1_override"


# -- lazy repair decisions ----------------------------------------------------

def test_defer_then_window_expiry_confirms():
    p, _ = make_policy(delay=10.0)
    p.observe_map(down(3), now=100.0)
    # inside the window: park (redundancy 3, one loss)
    assert p.should_defer(0, {3}, 1, 3, 4, now=105.0)
    assert 0 in p.parked
    assert p.counters["repair_deferred_stripes"] == 4
    # re-evaluation inside the window keeps parking, counts once
    assert p.should_defer(0, {3}, 1, 3, 4, now=108.0)
    assert p.counters["repair_deferred_stripes"] == 4
    # window expired: plan now, parked record dropped
    assert not p.should_defer(0, {3}, 1, 3, 4, now=110.0)
    assert 0 not in p.parked
    assert p.counters["repair_deferred_confirmed"] == 1
    assert p.clocks[3].state == DownClock.DOWN_CONFIRMED


def test_revive_cancels_parked_and_queues_recheck():
    p, _ = make_policy(delay=10.0)
    p.observe_map(down(3), now=100.0)
    assert p.should_defer(0, {3}, 1, 3, 4, now=101.0)
    assert p.should_defer(1, {3}, 1, 3, 2, now=101.5)
    revived = p.observe_map(UP, now=104.0)
    assert revived == [3]
    assert not p.parked                    # both PGs cancelled
    assert p.counters["repair_deferred_cancelled"] == 2
    assert p.take_recheck(0) == {3}
    assert p.take_recheck(1) == {3}
    assert p.take_recheck(0) == set()      # consumed once
    assert p.clocks[3].flaps == 1          # dwell 4 < delay 10
    # the re-check outcome feeds the counters the thrasher asserts
    p.note_recheck(0)
    p.note_recheck(5)
    assert p.counters["repair_cancel_noop"] == 1
    assert p.counters["repair_catchup_objects"] == 5


def test_m1_override_beats_delay():
    p, _ = make_policy(delay=3600.0)       # an hour of patience
    p.observe_map(down(2, 3), now=10.0)
    # redundancy 3, TWO losses -> 1 left: the delay loses immediately
    assert not p.should_defer(0, {2, 3}, 2, 3, 4, now=11.0)
    assert p.counters["repair_urgent_overrides"] == 1
    assert p.counters["repair_urgent_parked"] == 0
    # the holders are confirmed: a SINGLE-loss stripe of the same OSD
    # must not re-enter deferral afterwards
    assert not p.should_defer(1, {2}, 1, 3, 4, now=12.0)
    # m=1 codes are always urgent (any loss leaves zero redundancy)
    p2, _ = make_policy(delay=3600.0)
    p2.observe_map(down(1), now=0.0)
    assert not p2.should_defer(0, {1}, 1, 1, 4, now=1.0)


def test_stripe_budget_confirms_early():
    p, _ = make_policy(delay=3600.0,
                       osd_repair_deferred_max_stripes=10)
    p.observe_map(down(3), now=0.0)
    assert p.should_defer(0, {3}, 1, 3, 8, now=1.0)     # 8 parked
    # 8 + 6 > 10: the budget confirms instead of parking more
    assert not p.should_defer(1, {3}, 1, 3, 6, now=1.5)
    assert p.clocks[3].state == DownClock.DOWN_CONFIRMED
    assert p.counters["repair_deferred_confirmed"] == 1


def test_unknown_down_at_boot_is_eager():
    """A restarted primary cannot date a peer's down window — its
    FIRST map marks already-down peers confirmed (deferring an
    unknowable window would gamble safety on a guess)."""
    p = RepairPolicy(config=Config())
    p._config.set("osd_repair_delay", 3600.0)
    p.observe_map(down(4), now=0.0)        # first observation
    assert p.clocks[4].state == DownClock.DOWN_CONFIRMED
    assert p.clocks[4].confirmed_reason == "unknown_down_at_boot"
    assert not p.should_defer(0, {4}, 1, 3, 4, now=1.0)


def test_admin_out_confirms():
    p, _ = make_policy(delay=3600.0)
    p.observe_map(down(3), out_osds=[3], now=5.0)
    assert p.clocks[3].state == DownClock.DOWN_CONFIRMED
    assert p.clocks[3].confirmed_reason == "marked_out"


def test_live_config_reresolution():
    """The new options resolve AT CALL TIME through the layered
    Config — a committed `config set` retunes a running policy with
    no restart (the md_config_obs_t property the daemon relies on)."""
    p, cfg = make_policy(delay=0.0)
    p.observe_map(down(3), now=0.0)
    assert not p.should_defer(0, {3}, 1, 3, 4, now=1.0)   # policy off
    cfg.set("osd_repair_delay", 50.0)                     # turn it on
    assert p.should_defer(0, {3}, 1, 3, 4, now=2.0)
    cfg.set("osd_repair_delay", 0.0, level="override")    # off again
    assert not p.should_defer(0, {3}, 1, 3, 4, now=3.0)
    assert p.queue_order == "risk"
    cfg.set("osd_repair_queue_order", "pgid")
    assert p.queue_order == "pgid"


def test_exposure_time_accounting():
    p, _ = make_policy()
    p.note_exposure(0, True, now=10.0)
    p.note_exposure(0, True, now=11.0)     # steady state: no re-stamp
    assert p.exposed_pgs() == 1
    p.note_exposure(0, False, now=12.5)
    assert p.exposed_pgs() == 0
    assert p.counters["repair_time_at_m1_ms"] == 2500
    p.note_exposure(1, False, now=13.0)    # never exposed: no-op
    assert p.counters["repair_time_at_m1_ms"] == 2500


# -- risk ordering + exposure accounting -------------------------------------

class _FakePlan:
    def __init__(self, lost, helpers, wire_fraction=1.0):
        self.lost = list(lost)
        self.helper = list(helpers)
        if wire_fraction < 1.0:
            class _R:
                pass
            self.repair = _R()
            self.repair.wire_fraction = wire_fraction
        else:
            self.repair = None


def test_risk_key_and_order_plans():
    m = 3
    entries = [
        (0, _FakePlan([1], range(8)), set()),        # redundancy 2
        (1, _FakePlan([1, 2], range(8)), set()),     # redundancy 1 !
        (2, _FakePlan([1], range(4)), set()),        # red 2, cheaper
    ]

    def red(ps, plan):
        return m - len(plan.lost)

    ordered = order_plans(entries, red, mode="risk")
    assert [e[0] for e in ordered] == [1, 2, 0]
    # pgid mode keeps id order but COUNTS the inversions it ships
    counts = {}
    ordered_pg = order_plans(
        entries, red, mode="pgid",
        counter=lambda k, n: counts.__setitem__(
            k, counts.get(k, 0) + n))
    assert [e[0] for e in ordered_pg] == [0, 1, 2]
    assert counts["repair_risk_inversions"] == 1    # pg0 before pg1
    # risk mode ships zero inversions by construction
    counts2 = {}
    order_plans(entries, red, mode="risk",
                counter=lambda k, n: counts2.__setitem__(k, n))
    assert not counts2
    # the r14 cost tie-break: sub-chunk plans are cheaper than
    # full-row plans with the same helper count
    assert plan_helper_cost(_FakePlan([1], range(8), 0.25)) \
        < plan_helper_cost(_FakePlan([1], range(8)))
    assert risk_key(1, 2.0, 9) < risk_key(2, 1.0, 0)


def test_exposure_units_risk_vs_pgid():
    """The accounting metric BENCH_r17's rack-loss cell pins: with a
    few at-m-1 stripes buried late in PG-id order, risk order cuts
    cumulative exposure by well over half (exposed stripes complete
    first, so they stop accumulating while the bulk rebuilds)."""
    stripes = [(ps, 100.0, ps >= 28) for ps in range(32)]  # 4 at m-1
    pgid = exposure_units(stripes)
    risk = exposure_units(sorted(stripes, key=lambda s: not s[2]))
    assert risk < 0.5 * pgid
    assert exposure_units([]) == 0.0


# -- domain budgets -----------------------------------------------------------

def test_token_bucket_refill_and_debt():
    b = TokenBucket(rate=100.0, burst=200.0, now=0.0)
    assert b.take(150.0, now=0.0) == 0.0        # burst covers it
    w = b.take(100.0, now=0.0)                  # 50 left: wait 0.5s
    assert w == pytest.approx(0.5)
    assert b.take(100.0, now=1.0) == 0.0        # refilled 100 -> 150
    # an oversized cost clears from a FULL bucket (debt), then the
    # next grant throttles — no deadlock on one huge batch
    big = TokenBucket(rate=100.0, burst=100.0, now=0.0)
    assert big.take(500.0, now=0.0) == 0.0
    assert big.take(1.0, now=0.0) > 0.0
    big.retune(rate=1000.0, burst=50.0)
    assert big.tokens <= 50.0


def test_domain_budgets_starvation_freedom():
    """One rack draining its budget to zero must not delay another
    rack's grants — the property that keeps a burst rebuild in rack A
    from freezing rack B's repairs (both domains make progress)."""
    d = DomainBudgets()
    rate, burst = 1e6, 2e6
    # rack A pulls its whole burst, then throttles
    assert d.request({"rackA": 2e6}, rate, burst, now=0.0) == 0.0
    wait_a = d.request({"rackA": 1e6}, rate, burst, now=0.0)
    assert wait_a > 0.0
    # rack B still grants at the same instant
    assert d.request({"rackB": 1e6}, rate, burst, now=0.0) == 0.0
    # a two-domain pull is all-or-nothing: the grantable domain is
    # REFUNDED when the other refuses, so no tokens leak
    before = d._buckets["rackB"].tokens
    wait_ab = d.request({"rackA": 1e6, "rackB": 0.5e6}, rate, burst,
                        now=0.0)
    assert wait_ab > 0.0
    assert d._buckets["rackB"].tokens == pytest.approx(before)
    # after the refill interval rack A proceeds: progress, not
    # starvation
    assert d.request({"rackA": 1e6}, rate, burst,
                     now=wait_a + 0.01) == 0.0
    dump = d.dump()
    assert dump["rackA"]["throttled"] >= 2


def test_crush_domain_of():
    from ceph_tpu.crush.map import build_hierarchy
    m = build_hierarchy(16, osds_per_host=2, hosts_per_rack=2)
    r0 = m.domain_of(0)
    assert m.buckets[r0].type_id == 2               # a rack
    assert m.domain_of(3) == r0                     # same rack (4/host-pair)
    assert m.domain_of(15) != r0
    # flat fallback: no rack tier -> the highest ancestor is the key
    # (budgets degrade to one global bucket instead of exploding)
    from ceph_tpu.crush.map import CrushMap
    flat = CrushMap()
    flat.add_type(1, "host")
    flat.add_bucket(-1, 1, "straw2", [0, 1, 2])
    assert flat.domain_of(0) == flat.domain_of(2) == -1


# -- health -------------------------------------------------------------------

def test_health_pg_exposed():
    from ceph_tpu.mgr.health import HEALTH_WARN, health_checks

    class _Reports:
        def totals(self):
            return {"slow_ops": 0}

        def pg_states(self):
            return {"1.0": "active+degraded+exposed",
                    "1.1": "active+clean"}

        def daemons(self):
            return {}

        def report_ages(self):
            return {}

    h = health_checks(reports=_Reports())
    codes = {c["code"]: c for c in h["checks"]}
    assert "PG_EXPOSED" in codes
    assert codes["PG_EXPOSED"]["severity"] == HEALTH_WARN
    assert "1.0" in codes["PG_EXPOSED"]["detail"][0]
    assert h["status"] == HEALTH_WARN


# -- live wire tier (slow: one cluster boot each; the tier-1 live
# representative is the thrasher's transient smoke cell) ----------------------

@pytest.mark.slow
def test_lazy_repair_live_revive_cancels_with_zero_bytes():
    """End-to-end payoff on the wire tier (cephx off, small objects):
    kill an OSD, let the policy park the rebuild, revive inside the
    window — the cancel is a cursor re-check and the cluster-wide
    repair counters (decode rebuilds + helper pulls + backfill
    copies) move ZERO bytes. Then flip the delay live and watch the
    m-1 override beat it."""
    import time as _t

    from ceph_tpu.osd.standalone import StandaloneCluster
    c = StandaloneCluster(
        n_osds=8, profile="plugin=tpu_rs k=2 m=3 impl=bitlinear",
        pg_num=2, hb_interval=0.25, hb_grace=1.2)
    try:
        cl = c.client()
        cl.config_set("osd_repair_delay", 30.0)
        cl.write({f"o{i}": bytes([i]) * 300 for i in range(8)})
        c.wait_for_clean(timeout=60)

        def repair_bytes():
            return sum(d.ec_perf.get("recovered_bytes")
                       + d.ec_perf.get("recover_wire_bytes")
                       + d.perf.get("move_bytes")
                       for d in c.osds.values()
                       if not d._stop.is_set())

        def policy(key):
            return sum(d.repair_policy.counters.get(key, 0)
                       for d in c.osds.values()
                       if not d._stop.is_set())

        b0 = repair_bytes()
        victim = 7
        c.kill_osd(victim)
        c.wait_for_down(victim, timeout=30)
        deadline = _t.monotonic() + 20
        while _t.monotonic() < deadline:
            if policy("repair_deferred_stripes") > 0:
                break
            _t.sleep(0.2)
        assert policy("repair_deferred_stripes") > 0
        assert repair_bytes() == b0         # parked: nothing moved
        c.revive_osd(victim)
        c.wait_for_clean(timeout=60)
        _t.sleep(1.0)
        assert repair_bytes() == b0, \
            "within-window revive moved repair bytes"
        assert policy("repair_deferred_cancelled") >= 1
        assert policy("repair_cancel_noop") >= 1

        # live re-resolution + m-1 override: a 1-hour delay loses to
        # a second failure in the same PG
        cl.config_set("osd_repair_delay", 3600.0)
        d0 = next(d for d in c.osds.values()
                  if not d._stop.is_set() and d.backends)
        be = next(iter(d0.backends.values()))
        v1, v2 = [o for o in be.acting if o != d0.osd_id][:2]
        c.kill_osd(v1)
        c.kill_osd(v2)
        c.wait_for_down(v1, timeout=30)
        c.wait_for_down(v2, timeout=30)
        c.wait_for_clean(timeout=90)        # rebuilds NOW, not in 1h
        assert policy("repair_urgent_overrides") >= 1
        assert policy("repair_urgent_parked") == 0
        assert repair_bytes() > b0
        # the data survived the whole dance bit-exact
        for i in range(8):
            assert cl.read(f"o{i}") == bytes([i]) * 300
    finally:
        c.shutdown()
