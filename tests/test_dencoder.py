"""ceph-dencoder analog: every versioned wire type must round-trip
encode -> decode -> re-encode byte-exactly (ref: src/tools/
ceph-dencoder + the qa encoding-corpus determinism checks)."""

import importlib.util
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_dencoder():
    spec = importlib.util.spec_from_file_location(
        "ceph_dencoder", os.path.join(_REPO, "tools", "ceph_dencoder.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_DEN = _load_dencoder()


@pytest.mark.parametrize("name", sorted(_DEN.TYPES))
def test_roundtrip_byte_exact(name):
    t = _DEN.TYPES[name]
    obj = t["make"]()
    b1 = t["enc"](obj)
    obj2 = t["dec"](b1)
    b2 = t["enc"](obj2)
    assert b1 == b2, f"{name}: re-encode after decode differs"
    assert len(b1) > 0


@pytest.mark.parametrize("name", sorted(_DEN.TYPES))
def test_dump_is_jsonable(name):
    import json
    t = _DEN.TYPES[name]
    obj = t["dec"](t["enc"](t["make"]()))
    json.dumps(t["dump"](obj), default=str)


def test_encode_is_deterministic_across_instances():
    """Two independently built instances of the same logical value
    encode identically (no dict-order or id leakage)."""
    for name, t in _DEN.TYPES.items():
        assert t["enc"](t["make"]()) == t["enc"](t["make"]()), name
