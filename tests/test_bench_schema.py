"""Bench JSON schema smoke (Round-11/12 CI satellite): the benches'
machine-readable outputs carry the counters the acceptance numbers
are parsed from — this pins those schemas (and the committed
SCALE_r12.json artifact) so a refactor can't silently drop a key CI
reads."""

import json
import os

from tools import rados_bench

PCT_KEYS = {"p50_ms", "p95_ms", "p99_ms", "p999_ms", "max_ms"}
HEDGE_KEYS = {"hedge_issued", "hedge_wins", "hedge_losses",
              "hedge_cancelled", "degraded_dispatch",
              "degraded_served"}


REACTOR_KEYS = {"loops", "wakeups", "loop_lag_ms_avg",
                "writeq_flushes", "writeq_stalls"}


def test_rados_bench_json_schema(capsys):
    rados_bench.main([
        "seq", "--transport", "standalone", "--insecure",
        "--seconds", "0.4", "--object-size", "2048", "--batch", "2",
        "--num-osds", "4", "--pg-num", "2", "--op-shards", "2",
        "--profile", "plugin=tpu_rs k=2 m=1 impl=bitlinear",
        "--tenants", "2", "--hedge-delay-ms", "30", "--json"])
    out = json.loads(capsys.readouterr().out)
    # core stats + tail percentiles
    assert PCT_KEYS <= set(out)
    assert out["objects"] > 0 and out["ops_per_s"] > 0
    # hedge/degraded aggregate: all keys present, ints
    assert set(out["hedge"]) == HEDGE_KEYS
    assert all(isinstance(v, int) for v in out["hedge"].values())
    # per-tenant sections: entity + ops + percentiles + own counters
    assert set(out["tenants"]) == {"tenant0", "tenant1"}
    for t in out["tenants"].values():
        assert t["ops"] > 0
        assert PCT_KEYS <= set(t)
        assert HEDGE_KEYS <= set(t["hedge"])
    assert out["config"]["tenants"] == 2
    assert out["config"]["hedge_delay_ms"] == 30.0
    # attribution rides along (the r9 discipline): perf deltas exist
    assert "osd_total" in out["perf_delta"]
    assert "client" in out["perf_delta"]
    # r13: sharded-OSD + reactor attribution — per-shard occupancy
    # per daemon (every shard key present, counts are ints) and the
    # reactor loop-lag block the acceptance numbers are read from
    assert out["config"]["op_shards"] == 2
    assert out["config"]["msgr_workers"] == 1
    assert out["config"]["osd_procs"] is False
    assert out["shards"], "per-shard occupancy missing"
    served_total = 0
    for osd_name, shards in out["shards"].items():
        assert set(shards) == {"shard_0", "shard_1"}, osd_name
        for row in shards.values():
            assert isinstance(row["served"], int)
            assert isinstance(row["queued"], int)
            served_total += row["served"]
    assert served_total > 0
    assert REACTOR_KEYS <= set(out["reactor"])
    assert out["reactor"]["loops"] > 0


def test_bench_r13_artifact_pinned():
    """The committed r13 wire-bench artifact: schema keys CI parses,
    interleaved-median protocol evidence, and the floors the numbers
    must not silently regress below when re-committed."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r13.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "wire_r13/1"
    base = data["baselines"]["r12_head_measured"]
    r13 = data["r13"]
    for series in (base["write"], base["seq"], r13["write_default"],
                   r13["write_op_shards2"], r13["seq_default"]):
        assert len(series["mb_per_s_runs"]) >= 2
        assert series["mb_per_s_median"] > 0
    # the committed claim: r13 write beats the measured interleaved
    # r12 baseline; seq stays within noise of it
    assert (r13["write_op_shards2"]["mb_per_s_median"]
            > base["write"]["mb_per_s_median"])
    assert (r13["seq_default"]["mb_per_s_median"]
            > 0.9 * base["seq"]["mb_per_s_median"])
    acc = data["acceptance"]
    assert acc["write_vs_measured_baseline"] >= 1.1
    # per-shard + reactor attribution rides the committed cells
    cell = data["cells"]["write_op_shards2"]
    assert cell["config"]["op_shards"] == 2
    assert cell["shards"] and cell["reactor"]["loops"] > 0
    # the multi-process cell is present and annotated for 1-core
    assert "write_osd_procs_1core" in r13
    assert data["cells"]["write_osd_procs"]["config"]["osd_procs"]


REBALANCE_KEYS = {"moves", "rounds", "candidates_scored",
                  "candidates_per_s", "score_elapsed_s", "elapsed_s",
                  "max_dev_before", "max_dev_after", "spread_before",
                  "spread_after", "budget", "budget_used", "converged"}


def test_scale_sim_schema_and_acceptance_pinned():
    """The committed 10k-OSD / 1M-PG scale-sim artifact (r12): schema
    keys the docs/CI parse, plus the acceptance floors — balancer
    candidate throughput, 2x-imbalance convergence under budget, and
    the delta-vs-full wire-cost bound for single-OSD churn."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "SCALE_r12.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "scale_sim_r12/1"
    main = data["cells"]["scale_main"]
    for k in ("osds", "pg_num", "initial_map_launch_s",
              "placements_per_s", "churn_single_osd", "expansion",
              "failure", "rebalance", "follower_epoch", "inc_steps"):
        assert k in main, k
    assert main["osds"] == 10000 and main["pg_num"] == 1 << 20
    assert REBALANCE_KEYS <= set(main["rebalance"])
    for k in ("convergence_s", "upmap_pgs", "fraction_moved"):
        assert k in main["rebalance"], k
    bal2x = data["cells"]["balancer_2x"]
    assert REBALANCE_KEYS <= set(bal2x)
    for k in ("load_before_min", "load_before_max",
              "budget_respected", "convergence_s"):
        assert k in bal2x, k
    acc = data["acceptance"]
    assert acc["candidates_per_s"] >= 100_000
    assert acc["balancer_2x_max_dev_after"] <= 1.0
    assert acc["balancer_2x_converged"]
    assert acc["balancer_2x_budget_respected"]
    assert acc["single_osd_inc_to_full_ratio"] <= 0.05
