"""rados_bench JSON schema smoke (Round-11 CI satellite): the bench's
machine-readable output carries the hedge/degraded counters and
per-tenant percentiles the acceptance numbers are parsed from — this
pins that schema so a refactor can't silently drop a key CI reads."""

import json

from tools import rados_bench

PCT_KEYS = {"p50_ms", "p95_ms", "p99_ms", "p999_ms", "max_ms"}
HEDGE_KEYS = {"hedge_issued", "hedge_wins", "hedge_losses",
              "hedge_cancelled", "degraded_dispatch",
              "degraded_served"}


def test_rados_bench_json_schema(capsys):
    rados_bench.main([
        "seq", "--transport", "standalone", "--insecure",
        "--seconds", "0.4", "--object-size", "2048", "--batch", "2",
        "--num-osds", "4", "--pg-num", "2",
        "--profile", "plugin=tpu_rs k=2 m=1 impl=bitlinear",
        "--tenants", "2", "--hedge-delay-ms", "30", "--json"])
    out = json.loads(capsys.readouterr().out)
    # core stats + tail percentiles
    assert PCT_KEYS <= set(out)
    assert out["objects"] > 0 and out["ops_per_s"] > 0
    # hedge/degraded aggregate: all keys present, ints
    assert set(out["hedge"]) == HEDGE_KEYS
    assert all(isinstance(v, int) for v in out["hedge"].values())
    # per-tenant sections: entity + ops + percentiles + own counters
    assert set(out["tenants"]) == {"tenant0", "tenant1"}
    for t in out["tenants"].values():
        assert t["ops"] > 0
        assert PCT_KEYS <= set(t)
        assert HEDGE_KEYS <= set(t["hedge"])
    assert out["config"]["tenants"] == 2
    assert out["config"]["hedge_delay_ms"] == 30.0
    # attribution rides along (the r9 discipline): perf deltas exist
    assert "osd_total" in out["perf_delta"]
    assert "client" in out["perf_delta"]
