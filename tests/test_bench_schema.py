"""Bench JSON schema smoke (Round-11/12 CI satellite): the benches'
machine-readable outputs carry the counters the acceptance numbers
are parsed from — this pins those schemas (and the committed
SCALE_r12.json artifact) so a refactor can't silently drop a key CI
reads."""

import json
import os

import pytest

from tools import rados_bench

PCT_KEYS = {"p50_ms", "p95_ms", "p99_ms", "p999_ms", "max_ms"}
HEDGE_KEYS = {"hedge_issued", "hedge_wins", "hedge_losses",
              "hedge_cancelled", "degraded_dispatch",
              "degraded_served"}


REACTOR_KEYS = {"loops", "wakeups", "loop_lag_ms_avg",
                "writeq_flushes", "writeq_stalls"}

# r15 critical-path attribution block (both benches emit it; the
# categories are mgr/tracing.py CATEGORIES + total)
TRACE_KEYS = {"trace_id", "found", "daemons", "spans",
              "critical_path"}
TRACE_CP_KEYS = {"queue", "crypto", "encode", "store", "wire",
                 "other", "total"}

# r18 telemetry block (both benches emit it): interval series +
# merged lhist quantiles + SLO verdicts; rados_bench adds the
# observed-client-latency feed
TELEMETRY_KEYS = {"interval_s", "series", "quantiles", "slo"}

# r19 continuous-profiling block (rados/recovery/repair bench emit
# it): folded-stack flame summary + sampler overhead accounting
PROFILE_KEYS = {"daemons", "hz", "samples", "idle_samples",
                "categories", "category_share", "top_stacks",
                "sampler_overhead"}
# r22 network block (rados_bench + recovery_bench emit it): the
# mon's link matrix roll-up — threshold, bounded worst-first link
# rows, slow verdicts, and the cluster flow totals
NETWORK_KEYS = {"enabled", "threshold_ms", "links_total", "links",
                "slow", "flow_totals", "daemons_reporting"}
FLOW_TOTAL_KEYS = {"bytes_tx", "frames_tx", "bytes_rx", "frames_rx",
                   "stalls", "stall_time_s", "writeq_bytes",
                   "writeq_frames"}
LINK_ROW_KEYS = {"from", "to", "channel", "ewma_ms", "last_ms",
                 "min_ms", "max_ms", "count", "p50_ms", "p95_ms",
                 "p99_ms"}

# r21 capacity block (rados_bench + workload_bench emit it): the
# mon's df view at run end plus the two capacity-stall counters the
# acceptance numbers are read from (OSD failsafe rejections, client
# parked-write backoff)
CAPACITY_KEYS = {"cluster_full", "full_ratios", "total_bytes",
                 "total_used_bytes", "osds", "pools",
                 "writes_rejected_full", "client_full_backoff"}
RATIO_KEYS = {"nearfull", "backfillfull", "full", "failsafe"}


def _check_capacity_block(cap):
    assert set(cap) == CAPACITY_KEYS
    assert set(cap["full_ratios"]) == RATIO_KEYS
    assert set(cap["client_full_backoff"]) == {"count", "total_s"}
    assert isinstance(cap["cluster_full"], bool)
    assert isinstance(cap["writes_rejected_full"], int)
    for name, row in cap["osds"].items():
        assert {"total", "used", "avail", "ratio", "state"} \
            <= set(row), name


PROFILE_CATS = {"queue", "crypto", "encode", "store", "wire",
                "reactor", "other"}
QUANTILE_KEYS = {"p50_ms", "p95_ms", "p99_ms", "count"}
SLO_VERDICT_KEYS = {"name", "logger", "key", "quantile",
                    "threshold_ms", "window_s", "intervals",
                    "samples", "current_ms", "burn_fast",
                    "burn_slow", "breach"}
OCL_KEYS = {"source", "pool"} | QUANTILE_KEYS


def _check_network_block(net):
    assert NETWORK_KEYS <= set(net)
    assert isinstance(net["enabled"], bool)
    assert net["threshold_ms"] >= 0
    assert isinstance(net["links_total"], int)
    if net["flow_totals"]:
        assert FLOW_TOTAL_KEYS <= set(net["flow_totals"])
    for row in net["links"]:
        assert LINK_ROW_KEYS <= set(row)
        assert row["channel"] in {"hb", "store"}
        assert row["count"] >= 0 and row["ewma_ms"] >= 0


def _check_telemetry_block(tel, want_ocl=False):
    assert TELEMETRY_KEYS <= set(tel)
    for series in tel["series"].values():
        for pt in series:
            assert {"bucket", "t", "interval_s", "value"} <= set(pt)
    for q in tel["quantiles"].values():
        assert set(q) == QUANTILE_KEYS
    for v in tel["slo"]:
        assert SLO_VERDICT_KEYS <= set(v)
        assert isinstance(v["breach"], bool)
    if want_ocl:
        assert set(tel["observed_client_latency"]) == OCL_KEYS


def _check_profile_block(prof):
    assert PROFILE_KEYS <= set(prof)
    assert prof["daemons"]
    assert prof["hz"] > 0
    assert set(prof["categories"]) == PROFILE_CATS
    assert set(prof["category_share"]) == PROFILE_CATS
    for row in prof["top_stacks"]:
        assert {"category", "stack", "samples"} <= set(row)
        assert row["category"] in PROFILE_CATS
    ov = prof["sampler_overhead"]
    assert ov["busy_s"] >= 0 and ov["busy_share"] >= 0


def test_bench_r19_artifact_pinned():
    """The committed r19 continuous-profiling artifact: a live
    cephx+secure cluster assembles a flame from >= 3 daemons over the
    MgrReport pipe, `ceph_cli flame --speedscope` exports a valid
    document, profile_diff attributes the injected osd.op busy-spin
    to its own stack in the op-path category, and the interleaved
    ON/OFF guard holds the default-hz sampler at <= ~1.05x median
    pairwise slowdown."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r19.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "profile_r19/1"
    acc = data["acceptance"]
    assert acc["flame_daemons_reporting"] >= 3
    assert acc["speedscope_valid"] is True
    assert acc["burn_attributed_to_expected_category"] is True
    assert 0.95 <= acc["overhead_median_pairwise_slowdown"] <= 1.10
    burn = data["cells"]["burn_attribution"]
    assert burn["expected_category"] == "other"
    assert burn["burn_mover"]["category"] == "other"
    assert burn["burn_mover"]["delta_share"] > 0
    assert "_one_client_op" in burn["burn_mover"]["stack"]
    guard = data["cells"]["overhead_guard"]
    assert len(guard["pairs"]) >= 6
    assert all(p["on"] > 0 and p["off"] > 0 for p in guard["pairs"])
    assert set(data["cells"]["flame_assembly"]["categories"]) \
        == PROFILE_CATS


def test_bench_r21_artifact_pinned():
    """The committed r21 capacity-exhaustion artifact (generated by
    tools/capacity_bench.py): a live cephx+secure cluster driven FULL
    mid-write-window with ZERO surfaced client errors — writes park
    and drain exactly-once bit-exact, reads + the implicit-FULL_TRY
    delete keep serving; recovery into backfillfull targets parks
    (counted) while degraded reads serve; the REAL-capacity failsafe
    window bounces, parks and drains; and one-shot ENOSPC at every
    TinStore txn phase leaves the store fsck-clean across SIGKILL."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r21.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "capacity_r21/1"
    assert data["config"]["cephx"] and data["config"]["secure"]
    assert data["config"]["full_ratios"] == {
        "nearfull": 0.85, "backfillfull": 0.90,
        "full": 0.95, "failsafe": 0.97}
    acc = data["acceptance"]
    assert acc["client_op_errors"] == 0
    assert acc["reads_served_under_full"] > 0
    assert acc["delete_passed_under_full"] is True
    assert acc["parked_drained_fraction"] == 1.0
    assert acc["drained_bit_exact"] is True
    assert acc["recovery_parked_backfillfull"] > 0
    assert acc["degraded_reads_served_under_backfillfull"] > 0
    assert acc["failsafe_writes_rejected"] > 0
    assert acc["enospc_phases_covered"] == 6
    assert acc["enospc_all_fsck_clean"] is True
    fw = data["cells"]["full_window"]
    assert fw["writer_parked_during_window"] is True
    assert fw["parked_drained"] == fw["parked_writes"] > 0
    assert fw["full_backoff"]["count"] > 0
    assert fw["full_backoff"]["total_s"] > 0
    matrix = data["cells"]["enospc_matrix"]
    assert set(matrix) == {
        "txn.apply", "wal.append", "flush.segment-written",
        "flush.manifest-swapped", "compact.segments-written",
        "compact.manifest-swapped"}
    for phase, row in matrix.items():
        assert row["fired"] == 1, phase
        assert row["fsck_clean"] is True, phase
        assert row["acked_bit_exact_and_accepts_after"] is True, phase


def test_bench_r22_artifact_pinned():
    """The committed r22 network-observability artifact (generated by
    tools/netobs_bench.py): a one-way delay injected on one directed
    link of a live cephx+secure cluster flips OSD_SLOW_PING_TIME
    naming EXACTLY that link within two grace windows and clears
    after the heal; the r14 helper ranking reprices the degraded peer
    worst (net_helper_penalties pinned) and the mon link_cost feed
    separates the edges; and the whole plane ON holds wire write
    throughput at parity with OFF (median of >= 6 interleaved
    same-binary pairs inside the r15 noise envelope)."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r22.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "netobs_r22/1"
    assert data["config"]["cephx"] and data["config"]["secure"]
    acc = data["acceptance"]
    assert acc["flip_within_two_grace_windows"] is True
    assert acc["named_exact_link"] is True
    assert acc["cleared_after_heal"] is True
    assert acc["helper_repriced_counter_pinned"] is True
    assert 0.95 <= acc["overhead_median_pairwise"] <= 1.10
    ld = data["cells"]["link_degrade"]
    assert ld["degraded_link"].endswith("(hb)")
    assert ld["flip_s"] <= ld["flip_budget_s"]
    assert ld["named_exact_link"] is True and ld["detail"]
    assert all(ld["degraded_link"] in ln for ln in ld["detail"])
    assert ld["clear_s"] <= ld["clear_budget_s"]
    assert ld["slow_link_suspects"] >= 1
    ha = data["cells"]["helper_avoidance"]
    assert ha["degraded_priced_worst"] is True
    assert ha["net_helper_penalties_after"] \
        > ha["net_helper_penalties_before"]
    feed = ha["mon_link_cost_us"]
    assert feed["degraded_us"] > 10 * max(1, feed["healthy_us"])
    og = data["cells"]["overhead_guard"]
    assert len(og["pairs"]) >= 6
    assert all(p["on"] > 0 and p["off"] > 0 for p in og["pairs"])
    assert 0.95 <= og["median_pairwise_on_over_off"] <= 1.10


def test_bench_r18_artifact_pinned():
    """The committed r18 telemetry overhead-guard artifact: the
    history-ring + latency-histogram plane ON at defaults holds wire
    write MB/s and recovery obj/s at parity with OFF (median of >= 6
    interleaved same-binary pairs inside the r15 noise envelope)."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r18.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "telemetry_r18/1"
    for cell in ("wire_write", "recovery"):
        c = data["cells"][cell]
        assert len(c["pairs"]) >= 6
        assert all(p["on"] > 0 and p["off"] > 0 for p in c["pairs"])
        assert 0.95 <= c["median_pairwise_on_over_off"] <= 1.10
    acc = data["acceptance"]
    assert 0.95 <= acc["wire_write_median_pairwise"] <= 1.10
    assert 0.95 <= acc["recovery_median_pairwise"] <= 1.10


def test_slo_rule_schema_pinned():
    """The mgr_slo_rules grammar and the parsed-rule dict schema the
    `slo` mon command / bench verdicts render from."""
    from ceph_tpu.mgr.telemetry import parse_slo_rules
    rules = parse_slo_rules("client_read_p99 < 50ms over 5m")
    assert [r.to_dict() for r in rules] == [{
        "name": "client_read_p99", "logger": "osd",
        "key": "op_r_latency_hist", "quantile": 0.99,
        "threshold_ms": 50.0, "window_s": 300.0}]


def test_slo_rule_tenant_qualifier_pinned():
    """The r20 grammar extension: an optional `[tenant=...]` suffix
    scopes a client_observed rule to one tenant's own latency ring
    (the workload engine's per-tenant feed); the qualifier is only
    legal on the client_observed feed, and unqualified rules keep the
    exact pre-r20 dict shape (pinned above)."""
    import pytest

    from ceph_tpu.mgr.telemetry import parse_slo_rules
    rules = parse_slo_rules(
        "client_observed_p99 < 30ms over 2m [tenant=client.noisy]")
    assert [r.to_dict() for r in rules] == [{
        "name": "client_observed_p99[client.noisy]",
        "logger": "client", "key": "op_lat_hist", "quantile": 0.99,
        "threshold_ms": 30.0, "window_s": 120.0,
        "tenant": "client.noisy"}]
    with pytest.raises(ValueError, match="only applies"):
        parse_slo_rules("client_read_p99 < 30ms over 2m "
                        "[tenant=client.noisy]")


WL_TENANT_KEYS = {"entity", "klass", "stream_ops", "ops", "errors",
                  "routed", "digest", "mclock", "slo", "pre_kill",
                  "post_kill"}
WL_ROUTED_KEYS = {"read", "write_at", "append", "write_full"}


def test_workload_r20_artifact_pinned():
    """The committed r20 multi-tenant workload artifact: a live
    cephx+secure run of the 4-tenant builtin mix with a daemon kill
    mid-run. The acceptance floors: the noisy neighbor is visibly
    THROTTLED by its own mClock class (throttle counters > 0, its
    own SLO burning) while every other tenant's p99 SLO verdict
    stays green; the op streams replay bit-exactly from
    (profiles, seed); and the write_at block path ships less than
    half the full-stripe baseline's wire bytes per overwrite."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "WORKLOAD_r20.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "workload_r20/1"
    cfg = data["config"]
    assert cfg["cephx"] and cfg["secure"] and cfg["kill"]
    assert cfg["mclock_table"] and cfg["slo_rules"]
    assert set(data["tenants"]) == {"interactive", "streaming",
                                    "bursty", "noisy"}
    for name, row in data["tenants"].items():
        assert WL_TENANT_KEYS <= set(row), name
        assert row["ops"] > 0 and PCT_KEYS <= set(row)
        assert set(row["routed"]) == WL_ROUTED_KEYS
    # streams block: every digest is a sha256 the --repro path can
    # regenerate from the committed profiles + seed alone
    for name, srow in data["streams"].items():
        assert len(srow["digest"]) == 64 and srow["ops"] > 0, name
        assert srow["digest"] == data["tenants"][name]["digest"]
    # block-path routing did what the profiles declared
    assert data["tenants"]["interactive"]["routed"]["write_at"] > 0
    assert data["tenants"]["streaming"]["routed"]["write_full"] > 0
    assert data["tenants"]["bursty"]["routed"]["append"] > 0
    # the noisy neighbor: limit-bound by ITS class, SLO burning
    noisy = data["tenants"]["noisy"]
    assert noisy["mclock"]["throttled"] > 0
    assert noisy["mclock"]["profile"]["limit"] == 25.0
    assert any(v["breach"] for v in noisy["slo"])
    # every quiet tenant held its SLO, non-vacuously, across a kill
    for q in ("interactive", "streaming", "bursty"):
        vs = data["tenants"][q]["slo"]
        assert vs and all(v["intervals"] >= 2 and not v["breach"]
                          for v in vs), q
    # the mon-side per-tenant aggregate rode the MgrReport pipe
    assert "client.noisy" in data["mclock"]["mgr_aggregate"]
    assert data["mclock"]["mgr_aggregate"]["client.noisy"][
        "throttled"] > 0
    # amplification: the write_at cell stayed on the delta path and
    # beat the full-stripe baseline
    amp = data["amplification"]
    assert amp["write_at"]["rmw_ops"] > 0
    assert amp["write_at"]["full_fallbacks"] == 0
    # the r19 profiling plane attributed the run: folded flames from
    # the surviving daemons (the kill victim drops out of the block)
    pb = data["profile_block"]
    assert pb["samples"] > 0 and pb["daemons"]
    assert "category_share" in pb and pb["top_stacks"]
    acc = data["acceptance"]
    assert acc["noisy_visibly_throttled"] is True
    assert acc["noisy_throttled"] > 0
    assert acc["quiet_tenants_green"] is True
    assert acc["replay_digest_match"] is True
    assert acc["every_tenant_completed_ops"] is True
    assert acc["daemon_killed"] is True
    assert acc["overwrite_wire_vs_full_stripe"] <= 0.5
    assert data["recovery_kill"]["victim_killed_at_s"] > 0


def _check_trace_block(tr):
    assert TRACE_KEYS <= set(tr)
    assert tr["found"] is True
    assert tr["spans"] > 0
    assert set(tr["critical_path"]) == TRACE_CP_KEYS
    assert tr["critical_path"]["total"] > 0


def test_rados_bench_json_schema(capsys):
    # the 0.4 s window alone can finish ZERO ops under full-suite
    # load; the bench's min-ops guard (r16 deflake) keeps the window
    # open — load_factor-scaled — until every tenant owns an op, so
    # the percentile assertions below are never vacuous
    rados_bench.main([
        "seq", "--transport", "standalone", "--insecure",
        "--seconds", "0.4", "--object-size", "2048", "--batch", "2",
        "--num-osds", "4", "--pg-num", "2", "--op-shards", "2",
        "--profile", "plugin=tpu_rs k=2 m=1 impl=bitlinear",
        "--tenants", "2", "--hedge-delay-ms", "30", "--min-ops", "2",
        "--json"])
    out = json.loads(capsys.readouterr().out)
    # core stats + tail percentiles
    assert PCT_KEYS <= set(out)
    assert out["objects"] > 0 and out["ops_per_s"] > 0
    # hedge/degraded aggregate: all keys present, ints
    assert set(out["hedge"]) == HEDGE_KEYS
    assert all(isinstance(v, int) for v in out["hedge"].values())
    # per-tenant sections: entity + ops + percentiles + own counters
    assert set(out["tenants"]) == {"tenant0", "tenant1"}
    for t in out["tenants"].values():
        assert t["ops"] > 0
        assert PCT_KEYS <= set(t)
        assert HEDGE_KEYS <= set(t["hedge"])
    assert out["config"]["tenants"] == 2
    assert out["config"]["hedge_delay_ms"] == 30.0
    # attribution rides along (the r9 discipline): perf deltas exist
    assert "osd_total" in out["perf_delta"]
    assert "client" in out["perf_delta"]
    # r13: sharded-OSD + reactor attribution — per-shard occupancy
    # per daemon (every shard key present, counts are ints) and the
    # reactor loop-lag block the acceptance numbers are read from
    assert out["config"]["op_shards"] == 2
    assert out["config"]["msgr_workers"] == 1
    assert out["config"]["osd_procs"] is False
    assert out["shards"], "per-shard occupancy missing"
    served_total = 0
    for osd_name, shards in out["shards"].items():
        assert set(shards) == {"shard_0", "shard_1"}, osd_name
        for row in shards.values():
            assert isinstance(row["served"], int)
            assert isinstance(row["queued"], int)
            served_total += row["served"]
    assert served_total > 0
    assert REACTOR_KEYS <= set(out["reactor"])
    assert out["reactor"]["loops"] > 0
    # r15: the forced-sample probe's critical-path attribution — one
    # assembled trace spanning the client and at least one OSD
    _check_trace_block(out["trace"])
    assert any(d.startswith("client.") for d in out["trace"]["daemons"])
    assert any(d.startswith("osd.") for d in out["trace"]["daemons"])
    # r18: the telemetry block — series/quantiles/SLO verdicts from
    # the daemons' history rings, plus the observed-client-latency
    # feed (client-shipped histograms in this in-process run)
    _check_telemetry_block(out["telemetry"], want_ocl=True)
    assert out["telemetry"]["quantiles"][
        "osd.op_latency_hist"]["count"] > 0
    assert out["telemetry"]["observed_client_latency"]["count"] > 0
    assert {r["name"] for r in out["telemetry"]["slo"]} \
        == {"client_read_p99", "client_write_p99"}
    assert out["config"]["telemetry_off"] is False
    # r19: the continuous-profiling block — every OSD's sampling ring
    # folded into the flame summary CI diffs with profile_diff
    _check_profile_block(out["profile"])
    assert len(out["profile"]["daemons"]) == 4
    assert out["profile"]["samples"] >= 0
    # r21: the capacity block — the mon's df view plus the two
    # capacity-stall counters; this clean unbounded run never
    # laddered, so both counters pin at zero (non-vacuously: the df
    # rode the MgrReport statfs pipe for all 4 OSDs)
    _check_capacity_block(out["capacity"])
    assert out["capacity"]["cluster_full"] is False
    assert len(out["capacity"]["osds"]) == 4
    assert out["capacity"]["writes_rejected_full"] == 0
    assert out["capacity"]["client_full_backoff"]["count"] == 0
    # r22: the network block — the mon's link matrix + cluster flow
    # roll-up off the MgrReport side-field; even this short window
    # gets at least one report cycle (the bench holds the cluster
    # open past min-ops), so the flow totals are never vacuous
    _check_network_block(out["network"])
    assert out["network"]["enabled"] is True
    assert out["network"]["daemons_reporting"] >= 1
    assert out["network"]["flow_totals"]["bytes_tx"] > 0
    assert out["config"]["netobs_off"] is False


def test_bench_r13_artifact_pinned():
    """The committed r13 wire-bench artifact: schema keys CI parses,
    interleaved-median protocol evidence, and the floors the numbers
    must not silently regress below when re-committed."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r13.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "wire_r13/1"
    base = data["baselines"]["r12_head_measured"]
    r13 = data["r13"]
    for series in (base["write"], base["seq"], r13["write_default"],
                   r13["write_op_shards2"], r13["seq_default"]):
        assert len(series["mb_per_s_runs"]) >= 2
        assert series["mb_per_s_median"] > 0
    # the committed claim: r13 write beats the measured interleaved
    # r12 baseline; seq stays within noise of it
    assert (r13["write_op_shards2"]["mb_per_s_median"]
            > base["write"]["mb_per_s_median"])
    assert (r13["seq_default"]["mb_per_s_median"]
            > 0.9 * base["seq"]["mb_per_s_median"])
    acc = data["acceptance"]
    assert acc["write_vs_measured_baseline"] >= 1.1
    # per-shard + reactor attribution rides the committed cells
    cell = data["cells"]["write_op_shards2"]
    assert cell["config"]["op_shards"] == 2
    assert cell["shards"] and cell["reactor"]["loops"] > 0
    # the multi-process cell is present and annotated for 1-core
    assert "write_osd_procs_1core" in r13
    assert data["cells"]["write_osd_procs"]["config"]["osd_procs"]


REPAIR_KEYS = {"family", "helper_count", "wire_fraction",
               "helper_bytes_on_wire", "rebuilt_bytes",
               "repair_bytes_on_wire_per_rebuilt_byte", "vs_full_k",
               "vs_full_shard_reads", "range_batches",
               "helper_set_histogram"}


def test_bench_r14_artifact_pinned():
    """The committed r14 repair-locality artifact: schema keys CI
    parses, the per-cell `repair` blocks recovery_bench emits, and
    the acceptance floors — LRC k8m4l4 single-shard repair bytes on
    the wire <= 0.55x the RS full-k baseline, Clay helper bytes
    <= 0.75x full-shard reads. The metric is a COUNT over the
    planner's helper reads, so the floors are deterministic."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r14.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "recovery_r14/1"
    for cell in ("rs_k8m4", "lrc_k8m4l4", "clay_k8m4"):
        rep = data["cells"][cell]["repair"]
        assert REPAIR_KEYS <= set(rep), cell
        assert rep["helper_bytes_on_wire"] > 0
        assert rep["repair_bytes_on_wire_per_rebuilt_byte"] > 0
    assert data["cells"]["rs_k8m4"]["repair"]["family"] == "mds"
    assert data["cells"]["lrc_k8m4l4"]["repair"]["family"] \
        == "lrc_local"
    clay = data["cells"]["clay_k8m4"]["repair"]
    assert clay["family"] == "clay_planes"
    assert clay["range_batches"] >= 1
    acc = data["acceptance"]
    assert acc["lrc_vs_rs_full_k"] <= 0.55
    assert acc["clay_vs_full_shard_reads"] <= 0.75
    # the full-k baseline really is k reads per rebuilt byte
    assert acc["rs_full_k_bytes_per_rebuilt_byte"] == 8.0


@pytest.mark.slow
def test_recovery_bench_json_schema_live():
    """Live run of the r14 bench surface (slow sweep cell; the
    committed-artifact pin above is the tier-1 representative):
    recovery_bench --json emits the `repair` block with a local-group
    LRC plan and the bytes-on-wire ratio below full-k."""
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "recovery_bench.py"),
         "-P", "plugin=lrc", "-P", "k=4", "-P", "m=2", "-P", "l=3",
         "-P", "impl=bitlinear", "--objects", "4", "--size", "8192",
         "--json"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    data = json.loads(out.stdout)
    rep = data["repair"]
    assert REPAIR_KEYS <= set(rep)
    assert rep["family"] == "lrc_local"
    assert rep["vs_full_k"] < 1.0
    assert rep["helper_set_histogram"]["lrc_local"]
    # r15: the sampled recovery trace rides the same JSON
    _check_trace_block(data["trace"])
    assert data["trace"]["daemons"] == ["recovery_bench"]
    # r18: the telemetry block over the run's local history ring
    _check_telemetry_block(data["telemetry"])
    assert data["telemetry"]["quantiles"][
        "ec.recover_launch_time_hist"]["count"] > 0
    # r19: the bench's own sampling profile rides the same JSON
    _check_profile_block(data["profile"])
    assert data["profile"]["daemons"] == ["recovery_bench"]


RMW_KEYS = {"ops", "logical_bytes", "wire_bytes",
            "wire_bytes_per_logical_byte", "wire_bytes_per_op",
            "shard_ios", "shard_ios_per_op", "participants_expected",
            "preread_bytes", "append_fast_ops", "full_fallbacks",
            "journal_entries", "delta_launches"}
FULL_KEYS = {"logical_bytes", "wire_bytes",
             "wire_bytes_per_logical_byte", "wire_bytes_per_op"}


def test_bench_r16_artifact_pinned():
    """The committed r16 partial-stripe-write artifact: schema keys
    CI parses, the per-cell amplification blocks rados_bench emits,
    and the acceptance floors — for 4 KiB overwrites at k=8 m=3
    (4 MiB stripes, cephx+secure), bytes-on-wire per logical byte on
    the RMW path <= 0.25x the full-stripe-encode baseline measured
    in the same run, and exactly 1 data + m parity shards transact
    per op. Every metric is a COUNT, so the floors are
    deterministic."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r16.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "rmw_r16/1"
    for cname in ("overwrite_4k_k8m3", "append_4k_k8m3"):
        cell = data["cells"][cname]
        amp = cell["amplification"]
        assert RMW_KEYS <= set(amp["rmw"]), cname
        assert FULL_KEYS <= set(amp["full_stripe_baseline"]), cname
        assert amp["rmw"]["ops"] > 0
        assert amp["rmw"]["wire_bytes"] > 0
        assert cell["config"]["cephx"] and cell["config"]["secure"]
        assert cell["config"]["profile"] \
            == "plugin=tpu_rs k=8 m=3 impl=bitlinear"
        assert cell["config"]["chunk_size"] == 512 * 1024
        assert cell["config"]["overwrite_size"] == 4096
    acc = data["acceptance"]
    assert acc["overwrite_wire_vs_full_stripe"] <= 0.25
    assert acc["append_wire_vs_full_stripe"] <= 0.25
    # exactly 1 data + m parity shards move per RMW op, and the clean
    # overwrite cell never laddered to the full path
    assert acc["overwrite_shard_ios_per_op"] == 4.0
    assert acc["shard_ios_expected"] == 4
    assert acc["overwrite_full_fallbacks"] == 0
    # appends into stripe padding read no pre-image at all
    assert acc["append_preread_bytes"] == 0


@pytest.mark.slow
def test_rados_bench_overwrite_schema_live():
    """Live run of the r16 bench surface (slow sweep cell; the
    committed-artifact pin above is the tier-1 representative): the
    overwrite workload emits the amplification block, the RMW path
    beats the full-stripe baseline, and the shard-IO counter shows
    exactly 1 data + m parity participants."""
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "rados_bench.py"),
         "overwrite", "--transport", "standalone", "--insecure",
         "--object-size", "65536", "--batch", "2", "--num-osds", "8",
         "--pg-num", "2", "--rmw-ops", "8", "--overwrite-size",
         "2048", "--chunk-size", "8192",
         "--profile", "plugin=tpu_rs k=4 m=2 impl=bitlinear",
         "--json"],
        capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    data = json.loads(out.stdout)
    amp = data["amplification"]
    assert RMW_KEYS <= set(amp["rmw"])
    assert amp["rmw"]["ops"] == 8
    assert amp["rmw"]["shard_ios_per_op"] == 3.0   # 1 data + m=2
    assert amp["rmw"]["full_fallbacks"] == 0
    # r17 prepare coalescing: one fetch wave per delta group, frames
    # bounded by the participant count (vs 1+m getattrs + a pre-read
    # RTT per span before)
    assert amp["rmw"]["prepare_fetch_waves"] > 0
    assert amp["rmw"]["prepare_fetch_frames_per_op"] <= 3.0
    assert amp["ratio_vs_full_stripe"] < 1.0
    _check_trace_block(data["trace"])


STORM_PASS_KEYS = {"seed", "delay_s", "integrity", "pulses",
                   "revives_inside", "revives_inside_fraction",
                   "repair_bytes", "policy_counters", "verify"}
RACK_KEYS = {"downed_rack_osds", "pgs_touched", "lost_histogram",
             "stripes_at_m1", "exposure_pgid", "exposure_risk",
             "ratio_risk_vs_pgid"}


def test_bench_r17_artifact_pinned():
    """The committed r17 repair-policy storm artifact: schema keys CI
    parses and the acceptance floors — under a seeded transient-heavy
    kill/revive storm (>= 50% revives inside the window, cephx +
    secure), deferred repair moves <= 0.5x the eager baseline's
    repair bytes with ZERO data-loss/resurrection violations and
    every object bit-exact vs the full-decode oracle in BOTH
    integrity modes; under a simulated rack loss, cumulative
    stripe-time at m-1 with risk ordering <= 0.5x PG-id ordering.
    Every metric is a COUNT, so the floors are deterministic."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_r17.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "repair_r17/1"
    assert data["config"]["cephx"] and data["config"]["secure"]
    storm = data["cells"]["transient_storm"]
    for pname in ("eager", "deferred_host", "deferred_device"):
        p = storm[pname]
        assert STORM_PASS_KEYS <= set(p), pname
        assert p["verify"]["violations"] == 0
        assert p["verify"]["oracle_checked"] > 0
    assert storm["deferred_host"]["integrity"] == "host"
    assert storm["deferred_device"]["integrity"] == "device"
    # the same seeded schedule ran every pass, >= 50% inside
    assert storm["eager"]["seed"] == storm["deferred_host"]["seed"]
    assert storm["deferred_host"]["revives_inside_fraction"] >= 0.5
    # lazy repair engaged: stripes parked, inside revives cancelled
    # with zero-byte cursor re-checks
    for pname in ("deferred_host", "deferred_device"):
        pc = storm[pname]["policy_counters"]
        assert pc["repair_deferred_stripes"] > 0
        assert pc["repair_deferred_cancelled"] > 0
        assert pc["repair_cancel_noop"] > 0
        assert "repair_urgent_parked" not in pc     # invariant (b)
    assert RACK_KEYS <= set(data["cells"]["rack_loss"])
    assert data["cells"]["rack_loss"]["stripes_at_m1"] > 0
    acc = data["acceptance"]
    assert acc["deferred_vs_eager_repair_bytes"] <= 0.5
    assert acc["risk_vs_pgid_exposure"] <= 0.5
    assert acc["revives_inside_fraction"] >= 0.5
    assert acc["invariant_violations"] == 0
    assert acc["bit_exact_both_integrity_modes"] is True


CHURN_KEYS = {"events", "transient", "permanent", "confirmed",
              "cancelled", "urgent", "revives_inside",
              "revives_outside", "eager_bytes", "deferred_bytes",
              "catchup_bytes", "ratio_deferred_vs_eager", "config",
              "policy_counters"}


def test_scale_r17_repair_churn_pinned():
    """The committed 10k-OSD repair-churn day replay (r17): a day of
    transient+permanent failures at warehouse rates (arxiv 1309.0186
    shape: >= 90% transient, short downtimes) through the REAL
    RepairPolicy in virtual time. Floors: deferred repair prices at
    <= 0.5x the eager baseline, a majority of transient events
    cancel, and the no-delay control proves the model's two paths
    agree when the policy is off."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "SCALE_r17.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "scale_sim_r17/1"
    churn = data["cells"]["repair_churn_day"]
    control = data["cells"]["repair_churn_eager_control"]
    for cell in (churn, control):
        assert CHURN_KEYS <= set(cell)
    assert churn["config"]["osds"] == 10000
    assert churn["config"]["transient_fraction"] >= 0.9
    assert churn["config"]["osd_repair_delay_s"] > 0
    assert churn["policy_counters"]["repair_deferred_cancelled"] \
        == churn["cancelled"]
    acc = data["acceptance"]
    assert acc["deferred_vs_eager_bytes"] <= 0.5
    assert acc["cancelled_fraction"] >= 0.5
    assert acc["eager_control_ratio"] == 1.0


REBALANCE_KEYS = {"moves", "rounds", "candidates_scored",
                  "candidates_per_s", "score_elapsed_s", "elapsed_s",
                  "max_dev_before", "max_dev_after", "spread_before",
                  "spread_after", "budget", "budget_used", "converged"}


def test_scale_sim_schema_and_acceptance_pinned():
    """The committed 10k-OSD / 1M-PG scale-sim artifact (r12): schema
    keys the docs/CI parse, plus the acceptance floors — balancer
    candidate throughput, 2x-imbalance convergence under budget, and
    the delta-vs-full wire-cost bound for single-OSD churn."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "SCALE_r12.json")
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "scale_sim_r12/1"
    main = data["cells"]["scale_main"]
    for k in ("osds", "pg_num", "initial_map_launch_s",
              "placements_per_s", "churn_single_osd", "expansion",
              "failure", "rebalance", "follower_epoch", "inc_steps"):
        assert k in main, k
    assert main["osds"] == 10000 and main["pg_num"] == 1 << 20
    assert REBALANCE_KEYS <= set(main["rebalance"])
    for k in ("convergence_s", "upmap_pgs", "fraction_moved"):
        assert k in main["rebalance"], k
    bal2x = data["cells"]["balancer_2x"]
    assert REBALANCE_KEYS <= set(bal2x)
    for k in ("load_before_min", "load_before_max",
              "budget_respected", "convergence_s"):
        assert k in bal2x, k
    acc = data["acceptance"]
    assert acc["candidates_per_s"] >= 100_000
    assert acc["balancer_2x_max_dev_after"] <= 1.0
    assert acc["balancer_2x_converged"]
    assert acc["balancer_2x_budget_respected"]
    assert acc["single_osd_inc_to_full_ratio"] <= 0.05
