"""r19 continuous CPU profiling plane: span-tagged sampling profiler,
interval delta ring, bit-exact cluster flame merge, export formats,
and the before/after attribution diff.

One live-cluster cell at the end (ONE boot for the whole module — the
r15 CI rule): a cephx+secure cluster assembles a cluster CPU flame
from every daemon's sampling ring over the MgrReport pipe, serves it
as `profile cpu`, exports valid speedscope JSON through `ceph_cli
flame`, and goes quiet when `daemon_profile_hz` is set to 0.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from ceph_tpu.utils import profiler as prof_mod
from ceph_tpu.utils.perf_counters import fold_delta
from ceph_tpu.utils.profiler import (PROFILE_CATEGORIES,
                                     SamplingProfiler, category_of,
                                     category_split, collapsed_lines,
                                     merge_stacks, profile_block,
                                     push_span, speedscope, top_stacks)


def _bump(p: SamplingProfiler, cat: str, stack: str, n: int = 1):
    """Deterministic sample injection (white-box: the ring/merge
    tests must not depend on real thread scheduling)."""
    with p._lock:
        b = p._stacks.setdefault(cat, {})
        b[stack] = b.get(stack, 0) + n
        p._samples += n


class TestSpanTagging:
    def test_category_of_matches_trace_taxonomy(self):
        from ceph_tpu.mgr.tracing import CATEGORY_OF
        for name, cat in CATEGORY_OF.items():
            assert category_of(name) == cat
        assert category_of("no.such.span") == "other"
        # every trace category is a declared profile category
        assert set(CATEGORY_OF.values()) <= set(PROFILE_CATEGORIES)

    def test_push_is_free_when_no_sampler_active(self):
        assert prof_mod._ACTIVE == 0
        assert push_span("store.apply") is False
        assert threading.get_ident() not in prof_mod._SPAN_CATS

    def test_attribution_lands_in_span_category(self):
        """A thread inside span('store.apply') is sampled as `store`
        — the acceptance semantics (same units as `trace slow`)."""
        from ceph_tpu.utils.tracing import span
        with span("warmup"):     # resolve the lazy jax import OUTSIDE
            pass                 # the sampled window
        p = SamplingProfiler("t", hz=100.0)
        p._set_active(True)
        stop = threading.Event()
        ready = threading.Event()

        def worker():
            with span("store.apply"):
                ready.set()
                while not stop.is_set():
                    pass

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            assert ready.wait(5.0)
            skip = tuple(th.ident for th in threading.enumerate()
                         if th.ident != t.ident)
            for _ in range(20):
                p.sample_once(skip_tids=skip)
        finally:
            stop.set()
            t.join(2.0)
            p._set_active(False)
        d = p.dump()
        assert d["samples"] == 20
        assert sum((d["stacks"].get("store") or {}).values()) == 20
        # the collapsed stack names the worker frame, no line numbers
        assert any("test_profiler:worker" in s
                   for s in d["stacks"]["store"])

    def test_nested_spans_attribute_to_innermost(self):
        p = SamplingProfiler("t", hz=100.0)
        p._set_active(True)
        try:
            assert push_span("osd.op") is True          # -> other
            assert push_span("msgr.seal") is True       # -> crypto
            tid = threading.get_ident()
            assert prof_mod._SPAN_CATS[tid][-1] == "crypto"
            prof_mod.pop_span()
            assert prof_mod._SPAN_CATS[tid][-1] == "other"
            prof_mod.pop_span()
            assert tid not in prof_mod._SPAN_CATS
        finally:
            p._set_active(False)

    def test_hz_zero_records_nothing(self):
        """The off-switch invariant: an hz=0 profiler's thread idles
        without sampling and never activates span tagging."""
        p = SamplingProfiler("t", hz=0.0).start()
        try:
            time.sleep(0.5)
            assert p.dump()["samples"] == 0
            assert p.dump()["stacks"] == {}
            assert push_span("store.apply") is False
        finally:
            p.stop()


class TestIntervalRing:
    def test_tick_emits_pruned_deltas(self):
        clk = [1000.0]
        p = SamplingProfiler("t", hz=0, interval=10.0, ring=8,
                             now_fn=lambda: clk[0])
        _bump(p, "store", "a;b", 3)
        assert p.tick() is False          # baseline snapshot
        _bump(p, "store", "a;b", 2)
        _bump(p, "encode", "x;y", 1)
        clk[0] = 1010.0
        assert p.maybe_tick() is True
        ents = p.drain_unshipped()
        assert len(ents) == 1
        e = ents[0]
        assert e["bucket"] == 101
        assert e["samples"] == 3
        assert e["stacks"] == {"store": {"a;b": 2}, "encode": {"x;y": 1}}
        # same bucket -> no new entry
        clk[0] = 1011.0
        assert p.maybe_tick() is False
        # an interval with no new samples ships NO zero-count stacks
        clk[0] = 1020.0
        _bump(p, "store", "a;b", 1)
        assert p.maybe_tick() is True
        e2 = p.drain_unshipped()[0]
        assert e2["stacks"] == {"store": {"a;b": 1}}
        assert "encode" not in e2["stacks"]

    def test_ring_eviction_counts_unshipped_drops(self):
        clk = [0.0]
        p = SamplingProfiler("t", hz=0, interval=1.0, ring=4,
                             now_fn=lambda: clk[0])
        p.tick()
        for i in range(7):
            clk[0] += 1.0
            _bump(p, "other", "s", 1)
            p.tick()
        assert p.stats()["dropped_unshipped"] == 3     # 7 - ring 4
        # drained entries are consecutive and newest-aligned
        ents = p.drain_unshipped(limit=99)
        assert [e["seq"] for e in ents] == [4, 5, 6, 7]
        # nothing left after a drain; a new tick ships exactly one
        assert p.drain_unshipped() == []
        clk[0] += 1.0
        _bump(p, "other", "s", 1)
        p.tick()
        assert len(p.drain_unshipped()) == 1


class TestMerge:
    def test_cluster_merge_is_bit_exact(self):
        """The r18 rule on stacks: merge of per-daemon merges ==
        merge of all entries, exact integer equality."""
        from ceph_tpu.mgr.profiles import ProfileAggregator
        ents_a = [{"seq": 1, "t": 10.0, "bucket": 1, "interval_s": 10,
                   "hz": 10, "samples": 5, "busy_s": 0.0,
                   "stacks": {"store": {"a;b": 3}, "other": {"z": 2}}},
                  {"seq": 2, "t": 20.0, "bucket": 2, "interval_s": 10,
                   "hz": 10, "samples": 4, "busy_s": 0.0,
                   "stacks": {"store": {"a;b": 1, "a;c": 3}}}]
        ents_b = [{"seq": 1, "t": 10.0, "bucket": 1, "interval_s": 10,
                   "hz": 10, "samples": 7, "busy_s": 0.0,
                   "stacks": {"encode": {"e;f": 7}}}]
        agg = ProfileAggregator()
        agg.ingest("osd.0", {"entries": ents_a})
        agg.ingest("osd.1", {"entries": ents_b})
        hand = {}
        for e in ents_a + ents_b:
            hand = fold_delta(hand, e["stacks"])
        assert agg.flame() == hand
        assert agg.flame() == merge_stacks(
            [agg.flame("osd.0"), agg.flame("osd.1")])
        assert agg.flame("osd.0") == {"store": {"a;b": 4, "a;c": 3},
                                      "other": {"z": 2}}
        # interval alignment: bucket 1 folded across both daemons
        iv = {i["bucket"]: i for i in agg.intervals()}
        assert iv[1]["samples"] == 12
        assert iv[1]["daemons"] == ["osd.0", "osd.1"]
        assert iv[1]["categories"]["store"] == 3
        assert iv[1]["categories"]["encode"] == 7

    def test_stack_cap_folds_smallest_never_drops_samples(self):
        from ceph_tpu.mgr import profiles as profiles_mod
        from ceph_tpu.mgr.profiles import ProfileAggregator
        agg = ProfileAggregator()
        n = profiles_mod.MAX_STACKS + 50
        stacks = {"other": {f"s{i:05d}": i + 1 for i in range(n)}}
        agg.ingest("osd.0", {"entries": [
            {"seq": 1, "t": 1.0, "bucket": 0, "interval_s": 1,
             "hz": 10, "samples": 1, "busy_s": 0.0, "stacks": stacks}]})
        bucket = agg.flame("osd.0")["other"]
        assert len(bucket) <= profiles_mod.MAX_STACKS + 1
        assert "..." in bucket
        assert sum(bucket.values()) == sum(range(1, n + 1))
        assert agg.stats()["osd.0"]["stacks_folded"] == 50

    def test_cpu_cmd_parses_and_reports_unknown_daemon(self):
        from ceph_tpu.mgr.profiles import ProfileAggregator
        agg = ProfileAggregator()
        agg.ingest("osd.0", {"entries": [
            {"seq": 1, "t": 1.0, "bucket": 0, "interval_s": 1,
             "hz": 10, "samples": 2, "busy_s": 0.0,
             "stacks": {"store": {"a;b": 2}}}]})
        out = agg.cpu_cmd("")
        assert out["found"] and out["daemon"] == "cluster"
        assert out["samples"] == 2
        assert set(out["categories"]) == set(PROFILE_CATEGORIES)
        assert agg.cpu_cmd("osd.0 --collapsed")["collapsed"] \
            == ["store;a;b 2"]
        ss = agg.cpu_cmd("--speedscope")["speedscope"]
        assert ss["$schema"].startswith("https://www.speedscope.app")
        bad = agg.cpu_cmd("osd.9")
        assert bad["found"] is False and bad["daemons"] == ["osd.0"]
        with pytest.raises(ValueError):
            agg.cpu_cmd("--bogus")


class TestExports:
    STACKS = {"store": {"a;b": 3, "a;c": 1}, "encode": {"x": 2}}

    def test_category_split_declares_every_category(self):
        split = category_split(self.STACKS)
        assert set(split) == set(PROFILE_CATEGORIES)
        assert split["store"] == 4 and split["encode"] == 2
        assert split["wire"] == 0

    def test_top_stacks_deterministic_order(self):
        rows = top_stacks(self.STACKS, n=2)
        assert rows == [
            {"category": "store", "stack": "a;b", "samples": 3},
            {"category": "encode", "stack": "x", "samples": 2}]

    def test_collapsed_lines_roundtrip(self):
        lines = collapsed_lines(self.STACKS)
        assert "store;a;b 3" in lines
        total = 0
        for ln in lines:
            stack, cnt = ln.rsplit(" ", 1)
            cat = stack.split(";")[0]
            assert cat in PROFILE_CATEGORIES
            total += int(cnt)
        assert total == 6

    def test_speedscope_document_is_valid(self):
        doc = speedscope(self.STACKS, name="t")
        assert doc["$schema"] \
            == "https://www.speedscope.app/file-format-schema.json"
        prof = doc["profiles"][0]
        assert prof["type"] == "sampled"
        assert len(prof["samples"]) == len(prof["weights"]) == 3
        assert prof["endValue"] == sum(prof["weights"]) == 6
        nframes = len(doc["shared"]["frames"])
        for s in prof["samples"]:
            assert all(0 <= i < nframes for i in s)
        # first frame of each sample is the category
        cats = {doc["shared"]["frames"][s[0]]["name"]
                for s in prof["samples"]}
        assert cats == {"store", "encode"}

    def test_profile_block_folds_daemon_dumps(self):
        block = profile_block([
            {"name": "osd.0", "hz": 10.0, "samples": 4,
             "stacks": {"store": {"a;b": 3, "a;c": 1}},
             "sampler_busy_s": 0.1, "uptime_s": 10.0},
            {"name": "osd.1", "hz": 10.0, "samples": 2,
             "stacks": {"encode": {"x": 2}},
             "sampler_busy_s": 0.1, "uptime_s": 10.0}])
        assert block["daemons"] == ["osd.0", "osd.1"]
        assert block["samples"] == 6
        assert block["categories"]["store"] == 4
        assert block["category_share"]["encode"] == pytest.approx(1 / 3,
                                                                  abs=1e-3)
        assert block["top_stacks"][0]["stack"] == "a;b"
        assert block["sampler_overhead"]["busy_s"] == pytest.approx(0.2)
        assert block["sampler_overhead"]["busy_share"] \
            == pytest.approx(0.01)


class TestProfileDiff:
    def _block(self, cats, stacks=()):
        return {"samples": sum(cats.values()), "categories": cats,
                "top_stacks": [{"category": c, "stack": s,
                                "samples": n} for c, s, n in stacks]}

    def test_injected_burn_attributed_to_regressed_category(self):
        """The acceptance shape: a hot loop grows one category's
        share; the diff names that category and the mover stack."""
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        from profile_diff import diff_blocks
        before = self._block(
            {"queue": 0, "crypto": 10, "encode": 40, "store": 30,
             "wire": 0, "reactor": 10, "other": 10},
            [("encode", "a;encode", 40)])
        after = self._block(
            {"queue": 0, "crypto": 10, "encode": 40, "store": 30,
             "wire": 0, "reactor": 10, "other": 110},
            [("encode", "a;encode", 40),
             ("other", "standalone:_one_client_op;burn", 100)])
        d = diff_blocks(before, after, threshold=0.05)
        assert d["regressed"] == ["other"]
        assert d["verdict"].startswith("REGRESSED: other")
        assert d["top_movers"][0]["stack"] \
            == "standalone:_one_client_op;burn"
        # and a no-change pair stays quiet
        ok = diff_blocks(before, before)
        assert ok["regressed"] == [] and ok["verdict"] == "OK"

    def test_extract_block_accepts_artifact_and_raw_shapes(self):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        from profile_diff import extract_block
        block = self._block({"store": 4, "other": 1})
        assert extract_block({"profile": block}) is block
        assert extract_block(block) is block
        raw = extract_block({"store": {"a;b": 4}})
        assert raw["samples"] == 4 and raw["categories"]["store"] == 4
        with pytest.raises(ValueError):
            extract_block({"unrelated": 1})


# -- the live cell: ONE cluster boot for the whole module ------------------

def _lf() -> float:
    from ceph_tpu.chaos.thrasher import load_factor
    return load_factor()


@pytest.fixture(scope="module")
def live_cluster():
    from ceph_tpu.osd.standalone import StandaloneCluster
    c = StandaloneCluster(n_osds=3, pg_num=2, cephx=True,
                          secret=os.urandom(32))
    c.wait_for_clean(timeout=40 * _lf())
    yield c
    c.shutdown()


class TestLiveProfilingCell:
    """The acceptance cell: a cephx+secure cluster's monitor
    assembles a cluster CPU flame from >= 3 daemons over the
    MgrReport pipe, bit-exactly equal to the per-daemon fold; the
    command surface serves it end to end (mon cmd, asok, ceph_cli
    flame --speedscope); hz=0 stops sampling live."""

    def test_flame_assembles_and_exports(self, live_cluster, tmp_path):
        c = live_cluster
        cl = c.client()
        cl.config_set("mgr_history_interval", 0.5)
        cl.config_set("mgr_report_interval", 0.5)
        objs = {f"fl-{i}": bytes([i % 251]) * 512 for i in range(6)}
        cl.write(objs)
        mon = next(m for m in c.mons if not m._stop.is_set())
        deadline = time.monotonic() + 30 * _lf()
        while time.monotonic() < deadline:
            for n in sorted(objs):
                assert cl.read(n) == objs[n]
            st = mon.profiles.stats()
            if len(st) >= 3 and \
                    sum(d["samples"] for d in st.values()) > 30:
                break
            time.sleep(0.3)
        st = mon.profiles.stats()
        assert len(st) >= 3, f"profiles from {sorted(st)} only"

        # the mon command: cluster fold, schema-complete
        out = cl.mon_command("profile cpu")
        assert out["found"] and len(out["daemons"]) >= 3
        assert out["samples"] > 0
        assert set(out["categories"]) == set(PROFILE_CATEGORIES)
        assert out["top_stacks"]

        # bit-exact: cluster flame == fold of per-daemon flames
        cluster_flame = mon.profiles.flame()
        hand = merge_stacks(mon.profiles.flame(d)
                            for d in mon.profiles.daemons())
        assert cluster_flame == hand

        # per-daemon view + unknown daemon
        name = sorted(st)[0]
        assert cl.mon_command(f"profile cpu {name}")["daemon"] == name
        assert cl.mon_command("profile cpu no.such")["found"] is False

        # asok: one OSD's own cumulative profile
        osd = next(d for d in c.osds.values() if not d._stop.is_set())
        adump = osd._admin_obj("profile")
        assert adump["samples"] > 0 and adump["stacks"]

        # ceph_cli flame --speedscope writes a valid document
        ss_path = tmp_path / "flame.json"
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "ceph_cli.py"),
             "--asok-dir", c.admin_dir, "flame",
             "--speedscope", str(ss_path)],
            capture_output=True, text=True, timeout=60 * _lf())
        assert r.returncode == 0, r.stderr
        doc = json.loads(ss_path.read_text())
        assert doc["$schema"] \
            == "https://www.speedscope.app/file-format-schema.json"
        prof = doc["profiles"][0]
        assert prof["endValue"] == sum(prof["weights"]) > 0

        # `top` carries the observability drop gauges (satellite)
        top = cl.mon_command("top")
        gauges = top["observability"]["profiler"]
        assert len(gauges) >= 3
        assert all("dropped_unshipped" in g for g in gauges.values())

    def test_hz_zero_stops_sampling_live(self, live_cluster):
        c = live_cluster
        cl = c.client()
        cl.config_set("daemon_profile_hz", 0)
        osd = next(d for d in c.osds.values() if not d._stop.is_set())
        deadline = time.monotonic() + 10 * _lf()
        frozen = None
        while time.monotonic() < deadline:
            a = osd.profiler.dump()["samples"]
            time.sleep(0.5)
            b = osd.profiler.dump()["samples"]
            if a == b:
                frozen = a
                break
        assert frozen is not None, "sampler never stopped at hz=0"
        # and back on: sampling resumes from the live option
        cl.config_set("daemon_profile_hz", 10)
        deadline = time.monotonic() + 10 * _lf()
        while time.monotonic() < deadline:
            if osd.profiler.dump()["samples"] > frozen:
                break
            time.sleep(0.2)
        assert osd.profiler.dump()["samples"] > frozen
