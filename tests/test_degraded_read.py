"""Degraded-read fast path + hedged shard requests + per-tenant QoS
(wire tier). A read against a down, slow, or still-peering primary must
be served from any k surviving shards NOW — bit-exact vs healthy reads
— instead of waiting out detection + peering + recovery (ROADMAP item
3; the degraded-read tail of the online-EC characterization study,
arxiv 1709.05365). Hedged duplicates must be exactly-once through the
op window: losers cancelled, slots freed, no duplicate side effects.
"""

import time

import numpy as np
import pytest

from ceph_tpu.osd.standalone import StandaloneCluster


def corpus(seed, n=8, size=500):
    rng = np.random.default_rng(seed)
    return {f"dgr-{seed}-{i}":
            rng.integers(0, 256, size, np.uint8).tobytes()
            for i in range(n)}


def _window_clean(cl):
    """Exactly-once accounting: nothing left in flight, no leaked
    correlation-table entries (a cancelled loser must free its slot)."""
    assert cl.rpc.perf.get("inflight_ops") == 0
    assert not cl.rpc._pending


class TestDegradedReads:
    def test_served_bit_exact_with_primary_down_and_no_quorum(self):
        """The strongest form of 'no waiting on peering': with the mon
        quorum dead there will NEVER be a down-mark, a new map, or a
        recovered primary — so these reads can only succeed through
        the degraded fast path."""
        c = StandaloneCluster(n_osds=5, pg_num=4, op_timeout=3.0)
        try:
            c.wait_for_clean(timeout=20)
            cl = c.client(hedge_delay_ms=40)
            objs = corpus(1)
            cl.write(objs)
            healthy = {n: cl.read(n) for n in objs}
            assert healthy == objs

            # ---- per-tenant mClock while quorum still exists ----
            # without cephx the tenant identity is the messenger peer
            # name; this first client is "client.0"
            cl.config_set(
                "osd_mclock_scheduler_tenant_profiles",
                "client.0=5,9,0;client.1=1,1,50")
            cl2 = c.client()          # second entity = "client.1"
            for n in list(objs)[:3]:
                assert cl2.read(n) == objs[n]
            dumps = [cl.daemon(o, "dump_mclock")
                     for o in c.osd_ids()]
            tenants = {k: v for mc in dumps for k, v in mc.items()
                       if k.startswith("tenant:")}
            assert "tenant:client.0" in tenants
            assert "tenant:client.1" in tenants
            profiled = [mc["tenant:client.0"]["profile"]
                        for mc in dumps
                        if "tenant:client.0" in mc]
            assert {"reservation": 5.0, "weight": 9.0,
                    "limit": 0.0} in profiled
            served = sum(v["served"] for v in tenants.values())
            assert served > 0

            # ---- kill quorum, then the primary ----
            c.kill_mon(1)
            c.kill_mon(2)
            ps0 = cl.osdmap.object_to_pg(1, next(iter(objs)))[1]
            victim = cl.osdmap.pg_to_up_acting_osds(1, ps0)[2][0]
            c.kill_osd(victim)
            for n, want in objs.items():
                assert cl.read(n) == want, n
            pd = cl.perf.dump()
            assert pd["hedge_wins"] + pd["degraded_served"] > 0
            # map can never move: every later read of the dead
            # primary's PGs keeps riding the fast path
            for n, want in objs.items():
                assert cl.read(n) == want, n
            _window_clean(cl)

            # an object that never existed stays a KeyError, even
            # degraded (absence per the freshest quorum meta is real)
            with pytest.raises(KeyError):
                cl.read(f"dgr-never-{victim}")

            # ---- heal: quorum back -> detection -> clean -> normal
            c.revive_mon(1)
            c.wait_for_down(victim, timeout=30)
            c.wait_for_clean(timeout=60)
            for n, want in objs.items():
                assert cl.read(n) == want, n
        finally:
            c.shutdown()

    def test_hedge_beats_slow_primary_and_cancels_loser(self):
        """A primary that is merely SLOW (not dead): the hedge fires
        after the configured delay, the shard's degraded answer wins,
        the late primary reply is dropped on a cancelled handle, and
        accounting stays exactly-once."""
        c = StandaloneCluster(n_osds=4, pg_num=2, op_timeout=3.0)
        try:
            c.wait_for_clean(timeout=20)
            cl = c.client(hedge_delay_ms=30)
            objs = corpus(2, n=6)
            cl.write(objs)
            name = next(iter(objs))
            ps = cl.osdmap.object_to_pg(1, name)[1]
            slow = cl.osdmap.pg_to_up_acting_osds(1, ps)[2][0]
            in_pg = [n for n in objs
                     if cl.osdmap.object_to_pg(1, n)[1] == ps]
            # delay EVERY transmit of the slow primary by ~10x the
            # hedge delay; everyone else stays fast
            c.inject_delays(1, 300.0, osds=[slow], seed=7)
            try:
                for n in in_pg * 2:
                    assert cl.read(n) == objs[n], n
            finally:
                c.inject_delays(0, 0.0)
            pd = cl.perf.dump()
            assert pd["hedge_issued"] > 0
            # every issued hedge resolved: won, lost, or cancelled
            assert pd["hedge_wins"] + pd["hedge_losses"] \
                <= pd["hedge_issued"]
            assert pd["hedge_wins"] + pd["degraded_served"] > 0
            _window_clean(cl)
            # writes never hedge (exactly-once side effects): rewrite
            # through the slow window, then verify
            c.inject_delays(1, 120.0, osds=[slow], seed=8)
            try:
                repl = {n: bytes(reversed(v)) for n, v in objs.items()}
                cl.write(repl)
            finally:
                c.inject_delays(0, 0.0)
            before = cl.perf.dump()
            for n in repl:
                assert cl.read(n) == repl[n], n
            _window_clean(cl)
        finally:
            c.shutdown()

    def test_degraded_reads_do_not_wait_for_recovery(self):
        """Kill a primary with recovery throttled hard: reads complete
        while the cluster is provably NOT clean (wait_for_clean still
        times out), i.e. the fast path never queued behind the
        rebuild."""
        c = StandaloneCluster(n_osds=5, pg_num=4, op_timeout=3.0)
        try:
            c.wait_for_clean(timeout=20)
            cl = c.client(hedge_delay_ms=40)
            objs = corpus(3, n=10, size=900)
            cl.write(objs)
            # throttle recovery to a crawl so the rebuild window stays
            # open long after detection
            cl.config_set("osd_recovery_sleep", "15")
            cl.config_set("osd_recovery_batch", "1")
            ps0 = cl.osdmap.object_to_pg(1, next(iter(objs)))[1]
            victim = cl.osdmap.pg_to_up_acting_osds(1, ps0)[2][0]
            c.kill_osd(victim)
            for n, want in objs.items():
                assert cl.read(n) == want, n
            c.wait_for_down(victim, timeout=30)
            # recovery is in flight and throttled; reads still served
            with pytest.raises(TimeoutError):
                c.wait_for_clean(timeout=1.0)
            for n, want in objs.items():
                assert cl.read(n) == want, n
            _window_clean(cl)
            cl.config_set("osd_recovery_sleep", "0")
            cl.config_set("osd_recovery_batch", "128")
            c.wait_for_clean(timeout=90)
            for n, want in objs.items():
                assert cl.read(n) == want, n
        finally:
            c.shutdown()
