"""librados async I/O (refs: src/librados/librados.cc rados_aio_*,
AioCompletionImpl wait/is_complete/get_return_value semantics)."""

import threading

import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.osd.cluster import SimCluster


def mk(**kw):
    kw.setdefault("n_osds", 8)
    kw.setdefault("pg_num", 4)
    c = SimCluster(**kw)
    return c, Rados(c).open_ioctx()


class TestAio:
    def test_write_read_roundtrip(self):
        c, io = mk()
        comps = [io.aio_write_full(f"a{i}", f"payload-{i}".encode())
                 for i in range(16)]
        io.aio_flush(comps)
        assert all(cp.is_complete() for cp in comps)
        assert all(cp.get_return_value() > 0 for cp in comps)
        reads = [io.aio_read(f"a{i}") for i in range(16)]
        for i, cp in enumerate(reads):
            assert cp.get_return_value() == f"payload-{i}".encode()

    def test_callback_fires_off_caller_thread(self):
        c, io = mk()
        seen = {}
        done = threading.Event()

        def cb(comp):
            seen["thread"] = threading.current_thread().name
            seen["value"] = comp.get_return_value()
            done.set()
        io.aio_write_full("obj", b"with-callback", callback=cb)
        assert done.wait(10)
        assert seen["value"] == len(b"with-callback")
        assert seen["thread"] != threading.main_thread().name
        assert io.read("obj") == b"with-callback"

    def test_error_surfaces_via_get_return_value(self):
        c, io = mk()
        comp = io.aio_read("never-written")
        comp.wait_for_complete(10)
        with pytest.raises(KeyError):
            comp.get_return_value()

    def test_broken_callback_does_not_kill_the_pool(self):
        c, io = mk()

        def bad_cb(comp):
            raise RuntimeError("user bug")
        io.aio_write_full("x", b"one", callback=bad_cb).wait_for_complete(10)
        # pool still serves after the callback blew up
        comp = io.aio_write_full("y", b"two")
        assert comp.get_return_value() == 3
        assert io.read("y") == b"two"

    def test_flush_without_list_drains_queue(self):
        c, io = mk()
        comps = [io.aio_write_full(f"d{i}", bytes([i]) * 64)
                 for i in range(12)]
        io.aio_flush()
        assert all(cp.is_complete() for cp in comps)

    def test_buffer_snapshot_at_submit(self):
        """The caller may reuse its buffer immediately after submit —
        aio must have captured the bytes (librados copies into the
        op's bufferlist the same way)."""
        c, io = mk()
        buf = bytearray(b"original")
        comp = io.aio_write_full("snap-buf", buf)
        buf[:] = b"mutated!"
        comp.wait_for_complete(10)
        assert io.read("snap-buf") == b"original"

    def test_callbacks_complete_before_flush_returns(self):
        """librados order: wait/flush returning guarantees the
        callbacks ran — aggregates built in callbacks are whole."""
        c, io = mk()
        agg = []
        comps = [io.aio_write_full(f"agg{i}", b"x",
                                   callback=lambda cp, i=i:
                                   agg.append(i))
                 for i in range(10)]
        io.aio_flush(comps)
        assert sorted(agg) == list(range(10))

    def test_shutdown_joins_pool_and_sync_still_works(self):
        c, io = mk()
        io.aio_write_full("pre", b"data").wait_for_complete(10)
        io.rados.shutdown()
        assert io.rados._aio is None
        assert io.read("pre") == b"data"        # sync path unaffected
        # a later aio op lazily rebuilds the pool
        assert io.aio_read("pre").get_return_value() == b"data"

    def test_direct_accessors_safe_under_aio(self):
        """stat/list_objects serialize with in-flight aio writes (PG
        state is not thread-safe; the dispatch lock covers both)."""
        c, io = mk()
        comps = [io.aio_write_full(f"mix{i:03d}", bytes(64))
                 for i in range(50)]
        for _ in range(20):
            io.list_objects()       # must not see torn dict state
        io.aio_flush(comps)
        assert len([n for n in io.list_objects()
                    if n.startswith("mix")]) == 50

    def test_aio_remove_and_mixed_pipeline(self):
        c, io = mk()
        io.aio_write_full("victim", b"bye").wait_for_complete(10)
        rm = io.aio_remove("victim")
        rm.get_return_value()
        with pytest.raises(KeyError):
            io.read("victim")
