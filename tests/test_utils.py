"""Aux subsystem tests: PerfCounters, Config layering/observers,
Log ring + gates, OpTracker stage timing."""

import io
import time

import pytest

from ceph_tpu.utils.config import Config, Option
from ceph_tpu.utils.log import Log
from ceph_tpu.utils.op_tracker import OpTracker
from ceph_tpu.utils.perf_counters import (PerfCountersBuilder,
                                          PerfCountersCollection)


class TestPerfCounters:
    def build(self):
        return (PerfCountersBuilder("osd")
                .add_u64_counter("op_w", "writes")
                .add_u64("numpg", "placement groups")
                .add_time_avg("op_latency", "op latency")
                .add_histogram("op_size_hist", "op sizes", n_buckets=8)
                .create_perf_counters())

    def test_counter_gauge(self):
        c = self.build()
        c.inc("op_w")
        c.inc("op_w", 4)
        assert c.get("op_w") == 5
        c.set("numpg", 33)
        c.dec("numpg", 3)
        assert c.get("numpg") == 30
        with pytest.raises(TypeError):
            c.dec("op_w")  # counters are monotonic

    def test_time_avg_and_timer(self):
        c = self.build()
        c.tinc("op_latency", 0.5)
        c.tinc("op_latency", 1.5)
        got = c.get("op_latency")
        assert got["count"] == 2 and got["avg"] == 1.0
        with c.time("op_latency"):
            pass
        assert c.get("op_latency")["count"] == 3

    def test_histogram_buckets(self):
        c = self.build()
        for v in (1, 2, 3, 130):
            c.hinc("op_size_hist", v)
        assert sum(c.get("op_size_hist")) == 4
        assert c.get("op_size_hist")[7] == 1  # 130 -> bucket 7

    def test_histogram_bucket_boundaries_in_dump(self):
        """Slot i holds samples in [2^i, 2^(i+1)): exact powers of two
        land in their OWN slot, the last slot is the overflow clamp,
        and the dump carries the raw (non-cumulative) buckets."""
        c = self.build()
        for v in (1, 2, 4, 8, 127, 128, 1 << 30):  # 1<<30 >> 8 buckets
            c.hinc("op_size_hist", v)
        dumped = c.dump()["op_size_hist"]
        assert dumped[0] == 1          # 1
        assert dumped[1] == 1          # 2..3
        assert dumped[2] == 1          # 4..7
        assert dumped[3] == 1          # 8..15
        assert dumped[6] == 1          # 64..127
        assert dumped[7] == 2          # 128 + the overflow clamp
        assert sum(dumped) == 7

    def test_time_avg_math(self):
        """time_avg dump is (avgcount, sum); avg = sum/count exactly,
        0 when empty (no div-by-zero)."""
        c = self.build()
        assert c.get("op_latency") == {"sum": 0.0, "count": 0,
                                       "avg": 0.0}
        for s in (0.25, 0.25, 1.0):
            c.tinc("op_latency", s)
        got = c.get("op_latency")
        assert got == {"sum": 1.5, "count": 3, "avg": 0.5}
        d = c.dump()["op_latency"]
        assert d == {"avgcount": 3, "sum": 1.5}

    def test_concurrent_inc_from_threads(self):
        """inc/inc_many are atomic under the counter lock: N threads
        hammering one counter lose nothing."""
        import threading
        c = self.build()

        def worker():
            for _ in range(500):
                c.inc("op_w")
                c.inc_many((("op_w", 2),))
        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.get("op_w") == 8 * 500 * 3

    def test_dump_reset_roundtrip(self):
        """perf reset zeroes every kind; declarations (schema) and
        dump SHAPE survive — a post-reset dump has the same keys with
        zero values."""
        c = self.build()
        c.inc("op_w", 7)
        c.set("numpg", 4)
        c.tinc("op_latency", 1.0)
        c.hinc("op_size_hist", 9)
        before = c.dump()
        schema_before = c.schema()
        c.reset()
        after = c.dump()
        assert set(after) == set(before)
        assert after["op_w"] == 0 and after["numpg"] == 0
        assert after["op_latency"] == {"avgcount": 0, "sum": 0.0}
        assert sum(after["op_size_hist"]) == 0
        assert len(after["op_size_hist"]) == len(before["op_size_hist"])
        assert c.schema() == schema_before
        c.inc("op_w")               # still usable after reset
        assert c.get("op_w") == 1

    def test_declared_registry(self):
        from ceph_tpu.utils.perf_counters import is_declared
        self.build()
        assert is_declared("osd", "op_w")
        assert is_declared("osd", "op_size_hist")
        assert not is_declared("osd", "totally_made_up")

    def test_dump_delta_and_fold(self):
        from ceph_tpu.utils.perf_counters import dump_delta, fold_delta
        c = self.build()
        c.inc("op_w", 3)
        c.tinc("op_latency", 1.0)
        c.hinc("op_size_hist", 2)
        before = c.dump()
        c.inc("op_w", 4)
        c.tinc("op_latency", 0.5)
        c.hinc("op_size_hist", 2)
        delta = dump_delta({"osd": before}, {"osd": c.dump()})["osd"]
        assert delta["op_w"] == 4
        assert delta["op_latency"] == {"avgcount": 1, "sum": 0.5}
        assert sum(delta["op_size_hist"]) == 1
        # fold_delta(before, delta) == after
        refold = fold_delta({"osd": before},
                            {"osd": delta})["osd"]
        assert refold["op_w"] == c.dump()["op_w"]
        assert refold["op_size_hist"] == c.dump()["op_size_hist"]

    def test_collection_dump(self):
        coll = PerfCountersCollection()
        c = coll.add(self.build())
        c.inc("op_w")
        d = coll.dump()
        assert d["osd"]["op_w"] == 1
        assert d["osd"]["op_latency"] == {"avgcount": 0, "sum": 0.0}
        coll.remove("osd")
        assert coll.dump() == {}


class TestConfig:
    def test_defaults_and_layering(self):
        c = Config()
        assert c.get("osd_recovery_max_active") == 3
        c.load_file({"osd_recovery_max_active": "5"})
        assert c.get("osd_recovery_max_active") == 5
        c.set("osd_recovery_max_active", 7)           # mon layer
        assert c.get("osd_recovery_max_active") == 7
        c.set("osd_recovery_max_active", 9, level="override")
        assert c.get("osd_recovery_max_active") == 9
        c.rm("osd_recovery_max_active", level="override")
        assert c.get("osd_recovery_max_active") == 7

    def test_validation(self):
        c = Config()
        with pytest.raises(KeyError):
            c.get("nope")
        with pytest.raises(ValueError):
            c.set("osd_recovery_max_active", 0)       # min=1
        with pytest.raises(ValueError):
            c.set("osd_scrub_auto_repair", "maybe")
        c.set("osd_scrub_auto_repair", "true")
        assert c.get("osd_scrub_auto_repair") is True

    def test_observers(self):
        c = Config()
        seen = []
        c.observe("osd_heartbeat_grace", lambda k, v: seen.append((k, v)))
        c.set("osd_heartbeat_grace", 10.0)
        c.set("osd_heartbeat_grace", 10.0)  # no change -> no callback
        c.set("osd_heartbeat_grace", 12.0)
        assert seen == [("osd_heartbeat_grace", 10.0),
                        ("osd_heartbeat_grace", 12.0)]

    def test_diff(self):
        c = Config()
        c.set("debug_level", 5)
        d = c.diff()
        assert d == {"debug_level": {"value": 5, "level": "mon"}}


class TestLog:
    def test_gather_more_than_logged(self):
        sink = io.StringIO()
        lg = Log(max_recent=100, sink=sink)
        lg.set_level("ec", 1, gather=5)
        lg.dout("ec", 1, "printed and gathered")
        lg.dout("ec", 4, "gathered only")
        lg.dout("ec", 9, "dropped")
        printed = sink.getvalue()
        assert "printed and gathered" in printed
        assert "gathered only" not in printed
        recent = lg.dump_recent()
        assert any("gathered only" in ln for ln in recent)
        assert not any("dropped" in ln for ln in recent)

    def test_ring_bounded(self):
        lg = Log(max_recent=10, sink=None)
        for i in range(50):
            lg.dout("osd", 1, f"m{i}")
        recent = lg.dump_recent()
        assert len(recent) == 10
        assert "m49" in recent[-1]

    def test_crash_dump_format(self):
        sink = io.StringIO()
        lg = Log(max_recent=10, sink=None)
        lg.dout("osd", 1, "boom context")
        lg.dump_recent(file=sink)
        out = sink.getvalue()
        assert "begin dump of recent events" in out
        assert "boom context" in out


class TestOpTracker:
    def test_stages_and_history(self):
        tr = OpTracker(history_size=5)
        with tr.create_op("osd_op(client.1 write obj1)") as op:
            op.mark_event("queued")
            op.mark_event("encoded")
        assert tr.dump_ops_in_flight()["num_ops"] == 0
        hist = tr.dump_historic_ops()
        assert hist["num_ops"] == 1
        events = [e["event"] for e in
                  hist["ops"][0]["type_data"]["events"]]
        assert events == ["initiated", "queued", "encoded", "done"]

    def test_in_flight_and_slow(self):
        tr = OpTracker(complaint_time=0.01)
        op = tr.create_op("slow op")
        assert tr.dump_ops_in_flight()["num_ops"] == 1
        time.sleep(0.02)
        assert len(tr.slow_ops()) == 1
        op.finish()
        assert tr.slow_ops() == []

    def test_history_bounded_and_slowest(self):
        tr = OpTracker(history_size=3)
        for i in range(10):
            tr.create_op(f"op{i}").finish()
        assert tr.dump_historic_ops()["num_ops"] == 3
        assert tr.dump_historic_ops(by_duration=True)["num_ops"] == 3

    def test_exception_marks_failure(self):
        tr = OpTracker()
        with pytest.raises(RuntimeError):
            with tr.create_op("bad") as op:
                raise RuntimeError("x")
        events = [e["event"] for e in
                  tr.dump_historic_ops()["ops"][0]["type_data"]["events"]]
        assert any("failed: RuntimeError" in e for e in events)


class TestOpTrackerConfig:
    def test_thresholds_resolve_through_config(self):
        """osd_op_complaint_time / osd_op_history_* come from the
        config system LIVE — a runtime `config set` retunes a running
        tracker, no restart (the md_config_obs_t behavior)."""
        cfg = Config()
        tr = OpTracker(config=cfg)
        assert tr.complaint_time == 30.0          # schema default
        assert tr.history_size == 20
        cfg.set("osd_op_complaint_time", 0.01)
        op = tr.create_op("will be slow")
        time.sleep(0.02)
        assert len(tr.slow_ops()) == 1            # new threshold live
        cfg.set("osd_op_complaint_time", 60.0)
        assert tr.slow_ops() == []                # retuned again
        op.finish()

    def test_history_size_shrinks_live(self):
        cfg = Config()
        cfg.set("osd_op_history_size", 5)
        tr = OpTracker(config=cfg)
        for i in range(10):
            tr.create_op(f"op{i}").finish()
        assert tr.dump_historic_ops()["num_ops"] == 5
        cfg.set("osd_op_history_size", 2)
        assert tr.dump_historic_ops()["num_ops"] == 2
        assert tr.dump_historic_ops(
            by_duration=True)["num_ops"] == 2

    def test_ctor_fallbacks_without_config(self):
        tr = OpTracker(history_size=3, complaint_time=1.5)
        assert tr.history_size == 3
        assert tr.complaint_time == 1.5


def test_historic_ops_expire_by_age():
    tr = OpTracker(history_size=10, history_duration=0.05)
    tr.create_op("old").finish()
    time.sleep(0.08)
    tr.create_op("new").finish()
    ops = tr.dump_historic_ops()["ops"]
    descs = [o["description"] for o in ops]
    assert descs == ["new"]
    assert [o["description"] for o in
            tr.dump_historic_ops(by_duration=True)["ops"]] == ["new"]


# ------------------------------------------------ prometheus + tracing

class TestPrometheusExport:
    def test_exposition_format(self):
        from ceph_tpu.utils.perf_counters import (PerfCountersBuilder,
                                                  PerfCountersCollection)
        coll = PerfCountersCollection()
        pc = coll.add(PerfCountersBuilder("osd")
                      .add_u64_counter("ops", "client operations")
                      .add_u64("degraded", "degraded pgs")
                      .add_time_avg("op_lat")
                      .add_histogram("sizes", n_buckets=4)
                      .create_perf_counters())
        pc.inc("ops", 41)
        pc.set("degraded", 3)
        pc.tinc("op_lat", 0.5)
        pc.tinc("op_lat", 1.5)
        pc.hinc("sizes", 2)
        text = coll.prometheus_text()
        assert "# HELP ceph_tpu_osd_ops client operations" in text
        assert "# TYPE ceph_tpu_osd_ops counter" in text
        assert "ceph_tpu_osd_ops 41" in text
        assert "# TYPE ceph_tpu_osd_degraded gauge" in text
        assert "ceph_tpu_osd_degraded 3" in text
        assert "ceph_tpu_osd_op_lat_sum 2" in text
        assert "ceph_tpu_osd_op_lat_count 2" in text
        assert 'ceph_tpu_osd_sizes_bucket{le="+Inf"} 1' in text
        # every non-comment line is "name[{labels}] value"
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                assert len(line.rsplit(" ", 1)) == 2

    def test_cluster_counters_export(self):
        from cluster_helpers import corpus, make_cluster
        from ceph_tpu.utils.perf_counters import PerfCountersCollection
        c = make_cluster(pg_num=2)
        c.write(corpus(4, 200, seed=20))
        coll = PerfCountersCollection()
        coll.add(c.perf)
        text = coll.prometheus_text()
        assert "ceph_tpu_cluster_recovered_objects" in text
        assert "ceph_tpu_cluster_degraded_pgs 0" in text


class TestTracing:
    def test_annotation_import_memoized(self):
        """The jax.profiler import resolves ONCE at module level (the
        per-span try/import was measurable on the msgr hot path)."""
        from ceph_tpu.utils import tracing
        tracing._annotation("warm")           # resolve
        assert tracing._TRACE_ANNOTATION is not False
        resolved = tracing._TRACE_ANNOTATION
        tracing._annotation("again")
        assert tracing._TRACE_ANNOTATION is resolved

    def test_span_noop_and_counter(self):
        from ceph_tpu.utils.perf_counters import PerfCountersBuilder
        from ceph_tpu.utils.tracing import span
        pc = (PerfCountersBuilder("t").add_time_avg("lat")
              .create_perf_counters())
        with span("unit.test.span", counters=pc, key="lat"):
            pass
        got = pc.get("lat")
        assert got["count"] == 1 and got["sum"] >= 0

    @pytest.mark.slow
    def test_trace_capture_roundtrip(self, tmp_path):
        # nightly since r20: the jax.profiler device-trace capture
        # costs ~100 s of the 870 s tier-1 cap on a loaded box; the
        # span/counter tracing cells above keep the plane tier-1
        # profiler capture around a real device op; degrades gracefully
        import jax.numpy as jnp
        from ceph_tpu.utils.tracing import span, trace
        with trace(str(tmp_path)) as ok:
            with span("unit.capture"):
                jnp.arange(8).sum().block_until_ready()
        if ok:
            import os
            assert any(os.scandir(str(tmp_path)))


class TestOpTracking:
    def test_client_rpc_ops_are_tracked(self):
        from cluster_helpers import corpus, make_cluster
        from ceph_tpu.client.objecter import Objecter
        c = make_cluster(pg_num=2)
        ob = Objecter(c)
        objs = corpus(4, 200, seed=30)
        ob.write(objs)
        ob.read(list(objs))
        hist = c.op_tracker.dump_historic_ops()
        assert hist["num_ops"] >= 2
        descs = " ".join(o["description"] for o in hist["ops"])
        assert "client_rpc write" in descs
        assert "client_rpc read" in descs
        events = [ev["event"] for o in hist["ops"]
                  for ev in o["type_data"]["events"]]
        assert "reached_pg" in events
        inflight = c.op_tracker.dump_ops_in_flight()
        assert inflight.get("num_ops", inflight.get("num", 0)) == 0
