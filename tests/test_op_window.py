"""Windowed op pipelining (_Rpc window): out-of-order completion
matching, window-full backpressure, byte-budget gating, and the
lossless-replay guarantee that a reconnect with a NON-EMPTY window
resends unacked ops exactly once."""

import threading
import time

from ceph_tpu.msgr.messenger import Messenger
from ceph_tpu.osd.standalone import MOSDOp, MOSDOpReply, _Rpc
# bare import, matching how pytest imports test_msgr.py itself (no tests/
# __init__.py): a "tests.test_msgr" spelling would materialize a SECOND
# module object, re-run @register_message, and die on frame type 0x70
from test_msgr import wait_for


class FakeOsd:
    """A minimal MOSDOp responder with controllable reply behavior."""

    def __init__(self, name="osd.1"):
        self.msgr = Messenger(name)
        self.lock = threading.Lock()
        self.executed: list[int] = []          # rids, in arrival order
        self.exec_counts: dict[int, int] = {}  # rid -> times dispatched
        self.hold = threading.Event()          # replies wait for this
        self.hold.set()
        self.reverse_batch = 0                 # buffer N, reply reversed
        self._buffered: list[tuple[str, MOSDOp]] = []
        self.inflight = 0
        self.max_inflight = 0
        self.msgr.register_handler(MOSDOp.type_id, self._on_op)

    def _reply(self, peer, msg):
        self.msgr.send(peer, MOSDOpReply(msg.req_id, True, msg.kind,
                                         b"ok:%d" % msg.req_id))

    def _on_op(self, peer, msg):
        # record + hand off to a worker: the messenger dispatches on
        # the connection's reader thread, and a blocking handler there
        # would serialize the very pipelining this suite measures
        with self.lock:
            self.executed.append(msg.req_id)
            self.exec_counts[msg.req_id] = \
                self.exec_counts.get(msg.req_id, 0) + 1
            self.inflight += 1
            self.max_inflight = max(self.max_inflight, self.inflight)
            if self.reverse_batch:
                self._buffered.append((peer, msg))
                if len(self._buffered) < self.reverse_batch:
                    return
                batch, self._buffered = self._buffered, []
                for p, m in reversed(batch):
                    self.inflight -= 1
                    self._reply(p, m)
                return
        threading.Thread(target=self._serve, args=(peer, msg),
                         daemon=True).start()

    def _serve(self, peer, msg):
        self.hold.wait(10)
        with self.lock:
            self.inflight -= 1
        self._reply(peer, msg)

    def shutdown(self):
        self.msgr.shutdown()


def rig(window=0, window_bytes=0):
    osd = FakeOsd()
    client = Messenger("client.0")
    client.add_peer("osd.1", osd.msgr.addr)
    osd.msgr.add_peer("client.0", client.addr)
    rpc = _Rpc(client, MOSDOpReply.type_id, window=window,
               window_bytes=window_bytes)
    return osd, client, rpc


class TestWindow:
    def test_out_of_order_acks_match_by_req_id(self):
        osd, client, rpc = rig(window=8)
        try:
            osd.reverse_batch = 4   # replies come back REVERSED
            pends = [rpc.submit("osd.1",
                                lambda rid: MOSDOp(rid, True, "read",
                                                   b"x"))
                     for _ in range(4)]
            reps = [p.wait(10) for p in pends]
            # every handle got ITS op's reply despite reversed order
            for p, rep in zip(pends, reps):
                assert rep.ok and rep.blob == b"ok:%d" % p.rid
        finally:
            osd.shutdown()
            client.shutdown()

    def test_window_full_backpressure(self):
        osd, client, rpc = rig(window=2)
        try:
            osd.hold.clear()        # daemon sits on replies
            pends = []
            submitted = []

            def submit_five():
                for i in range(5):
                    pends.append(rpc.submit(
                        "osd.1", lambda rid: MOSDOp(rid, True, "read",
                                                    b"y")))
                    submitted.append(i)
            t = threading.Thread(target=submit_five, daemon=True)
            t.start()
            # only the window fits; the 3rd submit must BLOCK
            assert wait_for(lambda: len(submitted) == 2)
            time.sleep(0.3)
            assert len(submitted) == 2, "window did not backpressure"
            osd.hold.set()          # drain: completions free slots
            t.join(10)
            assert len(submitted) == 5
            for p in pends:
                assert p.wait(10).ok
            # the daemon never saw more than window ops concurrently
            assert osd.max_inflight <= 2, osd.max_inflight
        finally:
            osd.hold.set()
            osd.shutdown()
            client.shutdown()

    def test_byte_budget_backpressure(self):
        osd, client, rpc = rig(window=8, window_bytes=1000)
        try:
            osd.hold.clear()
            submitted = []

            def submit():
                for _ in range(3):
                    rpc.submit("osd.1",
                               lambda rid: MOSDOp(rid, True, "read",
                                                  b"z" * 600),
                               nbytes=600)
                    submitted.append(1)
            t = threading.Thread(target=submit, daemon=True)
            t.start()
            # 600 fits; 600+600 > 1000 -> second blocks while the
            # first is in flight
            assert wait_for(lambda: len(submitted) == 1)
            time.sleep(0.3)
            assert len(submitted) == 1, "byte budget did not gate"
            osd.hold.set()
            t.join(10)
            assert len(submitted) == 3
        finally:
            osd.hold.set()
            osd.shutdown()
            client.shutdown()

    def test_oversized_op_still_admitted_alone(self):
        # an op larger than the whole budget must not deadlock: it is
        # admitted when the window is otherwise empty
        osd, client, rpc = rig(window=4, window_bytes=100)
        try:
            rep = rpc.call("osd.1",
                           lambda rid: MOSDOp(rid, True, "read",
                                              b"w" * 5000))
            assert rep.ok
        finally:
            osd.shutdown()
            client.shutdown()

    def test_reconnect_with_open_window_resends_exactly_once(self):
        osd, client, rpc = rig(window=8)
        try:
            osd.hold.clear()        # ops arrive, replies held
            pends = [rpc.submit("osd.1",
                                lambda rid: MOSDOp(rid, True, "write",
                                                   b"data-%d" % i))
                     for i in range(3)]
            assert wait_for(lambda: len(osd.executed) == 3)
            # kill every live connection UNDER the open window; the
            # messenger replays unacked frames on reconnect, and the
            # receiver's seq dedup keeps redelivery exactly-once
            for conn in list(client._conns.values()):
                conn.close()
            time.sleep(0.1)
            osd.hold.set()
            # send one more op to force the reconnect + replay
            extra = rpc.submit("osd.1",
                               lambda rid: MOSDOp(rid, True, "write",
                                                  b"after"))
            for p in pends + [extra]:
                assert p.wait(15).ok
            # exactly-once: no rid was dispatched to the daemon twice
            dupes = {r: c for r, c in osd.exec_counts.items() if c > 1}
            assert not dupes, dupes
            assert len(osd.exec_counts) == 4
        finally:
            osd.hold.set()
            osd.shutdown()
            client.shutdown()
