"""Wire-encoding tests: primitive round-trips, the versioned-section
forward/backward-compat protocol, and the CrushMap/OSDMap/PGLog wire
forms (ref: src/include/encoding.h ENCODE_START/DECODE_FINISH
semantics; OSDMap/CrushWrapper/pg_log_t encode)."""

import numpy as np
import pytest

from ceph_tpu.crush.map import (CrushMap, Step, STEP_CHOOSELEAF_INDEP,
                                STEP_EMIT, STEP_TAKE, build_hierarchy,
                                ec_rule)
from ceph_tpu.osd.osdmap import OSDMap, PGPool
from ceph_tpu.osd.pglog import PGLog
from ceph_tpu.utils.encoding import Decoder, Encoder, EncodingError


class TestPrimitives:
    def test_roundtrip_all(self):
        e = (Encoder().u8(7).u16(65535).u32(1 << 31).u64(1 << 63)
             .i32(-5).i64(-(1 << 40)).f64(2.5).boolean(True)
             .string("héllo").blob(b"\x00\xff"))
        e.list([1, 2, 3], lambda en, v: en.u32(v))
        e.mapping({"a": 1}, lambda en, k: en.string(k),
                  lambda en, v: en.u32(v))
        d = Decoder(e.bytes())
        assert d.u8() == 7
        assert d.u16() == 65535
        assert d.u32() == 1 << 31
        assert d.u64() == 1 << 63
        assert d.i32() == -5
        assert d.i64() == -(1 << 40)
        assert d.f64() == 2.5
        assert d.boolean() is True
        assert d.string() == "héllo"
        assert d.blob() == b"\x00\xff"
        assert d.list(lambda dd: dd.u32()) == [1, 2, 3]
        assert d.mapping(lambda dd: dd.string(),
                         lambda dd: dd.u32()) == {"a": 1}

    def test_decode_past_end_raises(self):
        d = Decoder(Encoder().u16(1).bytes())
        d.u8()
        d.u8()
        with pytest.raises(EncodingError):
            d.u8()

    def test_unfinished_section_refuses_bytes(self):
        e = Encoder().start(1, 1).u8(1)
        with pytest.raises(EncodingError):
            e.bytes()


class TestVersionedSections:
    def test_old_reader_skips_new_fields(self):
        # v2 writer appends a field; v1 reader must skip it cleanly
        # and decode what follows the section
        e = Encoder()
        e.start(2, 1).u32(42).string("new-in-v2").finish()
        e.u32(99)  # field after the section
        d = Decoder(e.bytes())
        v = d.start(1)  # reader only understands v1
        assert v == 2
        assert d.u32() == 42
        d.finish()      # skips "new-in-v2"
        assert d.u32() == 99

    def test_incompatible_compat_raises(self):
        e = Encoder().start(5, 3).u32(1).finish()
        d = Decoder(e.bytes())
        with pytest.raises(EncodingError, match="incompatible"):
            d.start(2)  # reader v2 < compat 3

    def test_reader_cannot_overrun_section(self):
        e = Encoder().start(1, 1).u32(1).finish().u64(7)
        d = Decoder(e.bytes())
        d.start(1)
        d.u32()
        with pytest.raises(EncodingError):
            d.u32()  # would cross section boundary into the u64

    def test_nested_sections(self):
        e = Encoder().start(1, 1)
        e.start(3, 1).u8(9).string("inner-extra").finish()
        e.u8(5)
        e.finish()
        d = Decoder(e.bytes())
        d.start(1)
        assert d.start(1) == 3
        assert d.u8() == 9
        d.finish()
        assert d.u8() == 5
        d.finish()


class TestWireForms:
    @pytest.mark.slow   # ~24 s placement sweep; nightly (r10)
    def test_crushmap_roundtrip_same_placements(self):
        m = build_hierarchy(64, osds_per_host=4, hosts_per_rack=4)
        ec_rule(m, 1, choose_type=1)
        m2 = CrushMap.decode(m.encode())
        assert m2.encode() == m.encode()  # canonical bytes
        from ceph_tpu.crush.mapper import VectorMapper, full_weights
        w = full_weights(64)
        xs = np.arange(500, dtype=np.uint32)
        a = np.asarray(VectorMapper(m).do_rule(1, xs, w, 6))
        b = np.asarray(VectorMapper(m2).do_rule(1, xs, w, 6))
        assert np.array_equal(a, b)

    def test_crushmap_rejects_corrupt(self):
        m = build_hierarchy(8, osds_per_host=2, hosts_per_rack=2)
        raw = bytearray(m.encode())
        raw[2] = 0xFF  # clobber the section length
        with pytest.raises(EncodingError):
            CrushMap.decode(bytes(raw))

    def test_osdmap_roundtrip(self):
        m = build_hierarchy(16, osds_per_host=2, hosts_per_rack=4)
        ec_rule(m, 1, choose_type=1)
        om = OSDMap(m)
        om.add_pool(PGPool(1, pg_num=8, size=6, min_size=4,
                           crush_rule=1, is_erasure=True,
                           ec_profile={"k": "4", "m": "2"}))
        om.mark_down(3)
        om.mark_out(3)
        om.set_pg_temp((1, 2), [5, 6, 7, 8, 9, 10])
        om.set_primary_temp((1, 2), 6)
        om.config_set("osd_heartbeat_grace", "5.0")
        om2 = OSDMap.decode(om.encode())
        assert om2.config_kv == {"osd_heartbeat_grace": "5.0"}
        assert om2.epoch == om.epoch
        assert np.array_equal(om2.osd_weight, om.osd_weight)
        assert np.array_equal(om2.osd_up, om.osd_up)
        assert om2.pools[1].ec_profile == {"k": "4", "m": "2"}
        assert om2.pg_temp == om.pg_temp
        assert om2.primary_temp == om.primary_temp
        # identical placement behavior (pg_temp override included)
        for ps in range(8):
            assert (om.pg_to_up_acting_osds(1, ps)
                    == om2.pg_to_up_acting_osds(1, ps))

    def test_osdmap_config_kv_idempotent_mutators(self):
        """config_set/config_rm bump the epoch only on real change —
        the invariant the monitors' rebase-to-no-op pipe rests on
        (ref: ConfigMonitor::prepare_command no-op detection)."""
        m = build_hierarchy(8, osds_per_host=2, hosts_per_rack=2)
        ec_rule(m, 1, choose_type=1)
        om = OSDMap(m)
        e0 = om.epoch
        om.config_set("debug_level", "5")
        assert om.epoch == e0 + 1
        om.config_set("debug_level", "5")      # unchanged: no bump
        assert om.epoch == e0 + 1
        om.config_set("debug_level", "7")
        assert om.epoch == e0 + 2
        om.config_rm("nope")                   # absent: no bump
        assert om.epoch == e0 + 2
        om.config_rm("debug_level")
        assert om.epoch == e0 + 3
        assert om.config_kv == {}

    def test_pglog_roundtrip_preserves_missing_semantics(self):
        log = PGLog(max_entries=4)
        for n in ["a", "b", "c", "a", "d", "e", "f"]:
            log.append(n)
        log2 = PGLog.decode(log.encode())
        assert log2.head == log.head and log2.tail == log.tail
        for v in range(log.head + 1):
            assert log2.missing_since(v) == log.missing_since(v)

    def test_rule_step_program_survives(self):
        m = CrushMap()
        m.add_type(1, "host")
        m.add_bucket(-2, 1, "straw2", [0, 1], name="h0")
        m.add_bucket(-1, 2, "straw2", [-2], name="root")
        m.root_id = -1
        m.add_rule(3, [Step(STEP_TAKE, arg=-1),
                       Step(STEP_CHOOSELEAF_INDEP, arg=0, type_id=1),
                       Step(STEP_EMIT)], name="custom")
        m2 = CrushMap.decode(m.encode())
        r = m2.rules[3]
        assert r.name == "custom"
        assert [s.op for s in r.steps] == [STEP_TAKE,
                                           STEP_CHOOSELEAF_INDEP,
                                           STEP_EMIT]
        assert r.steps[0].arg == -1
