"""ObjectStore suite — ONE contract run against BOTH stores (the
reference's interface parameterization: src/test/objectstore/
store_test.cc runs the same suite over MemStore and BlueStore), plus
TinStore-only durability tests: WAL replay after SIGKILL, torn-tail
truncation, checkpoint cycling, verify-on-read, fsck, and a cluster
kill/revive that REALLY loses RAM (ref: src/os/bluestore/BlueStore.cc
_verify_csum/fsck; qa process-kill thrash semantics)."""

import os
import struct

import numpy as np
import pytest

from ceph_tpu.osd.memstore import MemStore, Transaction
from ceph_tpu.osd.tinstore import TinStore, TinStoreCorruption


@pytest.fixture(params=["mem", "tin", "tin-zlib"])
def store(request, tmp_path):
    if request.param == "mem":
        yield MemStore()
    elif request.param == "tin-zlib":
        # whole contract under inline compression (min_blob=1 so even
        # tiny compressible payloads take the compressed path)
        yield TinStore(str(tmp_path / "tin"), compression="zlib",
                       compression_min_blob=1)
    else:
        yield TinStore(str(tmp_path / "tin"))


def reopen(st):
    """Persistence boundary: for TinStore simulate SIGKILL + remount;
    for MemStore a no-op (its contract is RAM-lifetime only)."""
    if isinstance(st, TinStore):
        st.crash()
        st.remount()
    return st


class TestStoreContract:
    def test_write_read_roundtrip(self, store):
        t = (Transaction().create_collection("c")
             .write("c", "o", 0, b"hello world"))
        store.queue_transaction(t)
        assert bytes(store.read("c", "o")) == b"hello world"
        assert store.stat("c", "o") == 11

    def test_write_extends_with_zeros(self, store):
        store.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 4, b"xy"))
        assert bytes(store.read("c", "o")) == b"\x00\x00\x00\x00xy"

    def test_overwrite_middle(self, store):
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"abcdef").write("c", "o", 2, b"XY"))
        assert bytes(store.read("c", "o")) == b"abXYef"

    def test_truncate_shrink_and_grow(self, store):
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"abcdef").truncate("c", "o", 3))
        assert bytes(store.read("c", "o")) == b"abc"
        store.queue_transaction(Transaction().truncate("c", "o", 5))
        assert bytes(store.read("c", "o")) == b"abc\x00\x00"

    def test_remove_and_touch(self, store):
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"x").remove("c", "o").touch("c", "p"))
        assert not store.exists("c", "o")
        assert store.exists("c", "p")
        assert store.stat("c", "p") == 0

    def test_xattr_and_omap(self, store):
        store.queue_transaction(
            Transaction().create_collection("c").touch("c", "o")
            .setattr("c", "o", "hinfo", b"\x01\x02")
            .omap_set("c", "o", {b"k": b"v"}))
        assert store.getattr("c", "o", "hinfo") == b"\x01\x02"
        store.queue_transaction(Transaction().rmattr("c", "o", "hinfo"))
        with pytest.raises(KeyError):
            store.getattr("c", "o", "hinfo")

    def test_omap_rmkeys_and_clear(self, store):
        """OP_OMAP_RMKEYS / OP_OMAP_CLEAR (ref: src/os/ObjectStore.h):
        KV entries must be removable without killing the object."""
        store.queue_transaction(
            Transaction().create_collection("c").touch("c", "o")
            .omap_set("c", "o", {b"a": b"1", b"b": b"2", b"c": b"3"}))
        store.queue_transaction(
            Transaction().omap_rmkeys("c", "o", [b"a", b"missing"]))
        reopen(store)
        obj = store.collections["c"]["o"]
        assert dict(obj.omap) == {b"b": b"2", b"c": b"3"}
        store.queue_transaction(Transaction().omap_clear("c", "o"))
        reopen(store)
        assert dict(store.collections["c"]["o"].omap) == {}
        assert store.exists("c", "o")

    def test_remove_then_write_in_one_txn(self, store):
        # ops apply IN ORDER: a write after a remove starts from an
        # empty object — the old bytes must not resurrect (r4 review:
        # TinStore staging read pre-txn state)
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"AAAAAAAA"))
        store.queue_transaction(
            Transaction().remove("c", "o").write("c", "o", 0, b"BB"))
        assert bytes(store.read("c", "o")) == b"BB"
        assert store.stat("c", "o") == 2
        reopen(store)
        assert bytes(store.read("c", "o")) == b"BB"

    def test_rmcoll_then_recreate_in_one_txn(self, store):
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"old bytes"))
        store.queue_transaction(
            Transaction().remove_collection("c").create_collection("c")
            .write("c", "o", 3, b"xy"))
        assert bytes(store.read("c", "o")) == b"\x00\x00\x00xy"

    def test_omap_rmkeys_missing_object_is_noop(self, store):
        store.queue_transaction(Transaction().create_collection("c"))
        store.queue_transaction(
            Transaction().omap_rmkeys("c", "ghost", [b"k"])
            .omap_clear("c", "ghost"))
        assert not store.exists("c", "ghost")

    def test_collections_listing(self, store):
        store.queue_transaction(
            Transaction().create_collection("b").create_collection("a")
            .write("a", "z", 0, b"1").write("a", "y", 0, b"2"))
        assert store.list_collections() == ["a", "b"]
        assert store.list_objects("a") == ["y", "z"]
        store.queue_transaction(Transaction().remove_collection("b"))
        assert store.list_collections() == ["a"]

    def test_validation_aborts_whole_txn(self, store):
        store.queue_transaction(Transaction().create_collection("c"))
        bad = (Transaction().write("c", "o", 0, b"data")
               .write("nope", "o", 0, b"data"))
        with pytest.raises(KeyError):
            store.queue_transaction(bad)
        # all-or-nothing: the eligible first op must NOT have applied
        assert not store.exists("c", "o")

    def test_missing_reads_raise(self, store):
        with pytest.raises(KeyError):
            store.read("c", "o")
        store.queue_transaction(Transaction().create_collection("c"))
        with pytest.raises(KeyError):
            store.read("c", "o")


class TestTinStoreDurability:
    def test_kill_loses_nothing_committed(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"committed bytes")
            .setattr("c", "o", "a", b"xattr")
            .omap_set("c", "o", {b"k": b"v"}))
        st.crash()                      # SIGKILL: RAM gone
        with pytest.raises(RuntimeError):
            st.read("c", "o")
        st.remount()                    # recovery = WAL replay only
        assert bytes(st.read("c", "o")) == b"committed bytes"
        assert st.getattr("c", "o", "a") == b"xattr"
        assert st.committed_txns == 1

    def test_many_txns_replay_in_order(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(Transaction().create_collection("c"))
        rng = np.random.default_rng(3)
        want = {}
        for i in range(40):
            data = rng.integers(0, 256, int(rng.integers(1, 400)),
                                np.uint8)
            name = f"o{i % 7}"         # overwrites interleave creates
            st.queue_transaction(
                Transaction().write("c", name, 0, data)
                .truncate("c", name, len(data)))
            want[name] = data.tobytes()
        reopen(st)
        for name, data in want.items():
            assert bytes(st.read("c", name)) == data

    def test_torn_tail_record_dropped(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 0, b"ok"))
        st.crash()
        # simulate crash mid-append: garbage half-record at the tail
        with open(os.path.join(str(tmp_path / "s"), "wal.log"), "ab") as f:
            f.write(struct.pack("<IQI", 0x544E4952, 99, 1 << 20))
            f.write(b"\x01\x02\x03")    # body cut short
        st.remount()
        assert bytes(st.read("c", "o")) == b"ok"
        # the torn bytes were truncated away; new commits extend cleanly
        st.queue_transaction(Transaction().write("c", "p", 0, b"post"))
        reopen(st)
        assert bytes(st.read("c", "p")) == b"post"

    def test_mid_log_corruption_fails_loudly(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c").write("c", "a", 0, b"1"))
        st.queue_transaction(Transaction().write("c", "b", 0, b"2"))
        st.crash()
        wal = os.path.join(str(tmp_path / "s"), "wal.log")
        with open(wal, "r+b") as f:
            f.seek(20)                  # inside record 1's body
            f.write(b"\xff\xff")
        with pytest.raises(TinStoreCorruption):
            st.remount()
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep["errors"]

    def test_checkpoint_cycle_and_recovery(self, tmp_path):
        st = TinStore(str(tmp_path / "s"), wal_max_bytes=2000)
        st.queue_transaction(Transaction().create_collection("c"))
        rng = np.random.default_rng(5)
        want = {}
        for i in range(30):             # crosses several checkpoints
            data = rng.integers(0, 256, 150, np.uint8)
            st.queue_transaction(Transaction().write("c", f"o{i}", 0, data))
            want[f"o{i}"] = data.tobytes()
        # crossing wal_max_bytes flushed the KV memtable to at least
        # one sorted segment under the (crc-sealed) MANIFEST
        assert os.path.exists(os.path.join(str(tmp_path / "s"), "MANIFEST"))
        ks = st.kv_stats()
        assert ks["flushes"] >= 1 and ks["segments"] >= 1
        reopen(st)
        for name, data in want.items():
            assert bytes(st.read("c", name)) == data
        assert st.committed_txns == 31

    def test_umount_checkpoint_then_clean_mount(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 0, b"z"))
        st.umount()
        # after umount the WAL is empty; state lives in the checkpoint
        assert os.path.getsize(
            os.path.join(str(tmp_path / "s"), "wal.log")) == 0
        st.remount()
        assert bytes(st.read("c", "o")) == b"z"

    def test_verify_on_read_catches_ram_rot(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"clean bytes"))
        st.collections["c"]["o"].data[3] ^= 0x40    # bypasses the WAL
        with pytest.raises(TinStoreCorruption):
            st.read("c", "o")

    def test_segment_corruption_detected_at_mount(self, tmp_path):
        # umount flushes the memtable into a sealed segment; flip a
        # byte inside it — the seal must fail the next mount AND fsck
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"will be sealed"))
        st.umount()
        segs = [f for f in os.listdir(str(tmp_path / "s"))
                if f.startswith("seg-") and f.endswith(".tdb")]
        assert segs, "umount should have flushed a segment"
        with open(os.path.join(str(tmp_path / "s"), segs[0]),
                  "r+b") as f:
            f.seek(12)
            f.write(b"\xaa")
        with pytest.raises(TinStoreCorruption):
            st.remount()
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep["errors"]

    def test_manifest_corruption_detected_at_mount(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"manifest guard"))
        st.umount()
        with open(os.path.join(str(tmp_path / "s"), "MANIFEST"),
                  "r+b") as f:
            f.seek(6)
            f.write(b"\xaa")
        with pytest.raises(TinStoreCorruption):
            st.remount()
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep["errors"]

    def test_fsck_clean_report(self, tmp_path):
        st = TinStore(str(tmp_path / "s"), wal_max_bytes=10 << 20)
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o1", 0, b"abc").write("c", "o2", 0, b"def"))
        st.queue_transaction(Transaction().write("c", "o3", 0, b"ghi"))
        st.crash()
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep["objects"] == 3 and rep["wal_records"] == 2
        assert not rep["bad_objects"] and not rep["errors"]
        assert not rep["torn_tail"] and not rep["extent_errors"]
        # 3 objects × one 4 KiB allocation unit each, all accounted
        assert rep["used_bytes"] == 3 * 4096
        assert rep["device_bytes"] >= rep["used_bytes"]


class TestTinStoreBlockPlane:
    """The block-device plane (ref: src/os/bluestore/BlueStore.cc
    _do_read cache path, BitmapAllocator): bounded cache, extent
    allocator reuse, metadata-only checkpoints."""

    def test_bounded_cache_serves_4x_dataset(self, tmp_path):
        # 64 objects x 16 KiB = 1 MiB working set through a 256 KiB
        # cache: every byte must serve exactly, the budget must hold,
        # and eviction must force device reads
        budget = 256 << 10
        st = TinStore(str(tmp_path / "s"), cache_bytes=budget)
        rng = np.random.default_rng(7)
        objs = {f"o{i:02d}": rng.integers(0, 256, 16384,
                                          np.uint8).tobytes()
                for i in range(64)}
        t = Transaction().create_collection("c")
        for name, data in objs.items():
            t.write("c", name, 0, data)
        st.queue_transaction(t)
        for _ in range(2):
            for name, want in objs.items():
                assert bytes(st.read("c", name)) == want
                assert st.cache_stats()["bytes"] <= budget
        assert st.cache_stats()["misses"] > 0
        st.crash()
        st.remount()
        for name, want in objs.items():
            assert bytes(st.read("c", name)) == want
            assert st.cache_stats()["bytes"] <= budget

    def test_checkpoint_is_metadata_only(self, tmp_path):
        # 4 MiB of object data; the flushed KV plane must stay tiny
        # (extent refs, not bytes) — the r3 O(store) serialize is gone
        st = TinStore(str(tmp_path / "s"))
        big = bytes(range(256)) * (4 << 12)
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "big", 0, big))
        st.checkpoint()
        d = str(tmp_path / "s")
        kv_bytes = sum(
            os.path.getsize(os.path.join(d, f)) for f in os.listdir(d)
            if f == "MANIFEST" or f.endswith(".tdb"))
        assert kv_bytes < 16 << 10, \
            f"KV plane {kv_bytes}B should be metadata-only"
        st.crash()
        st.remount()
        assert bytes(st.read("c", "big")) == big

    def test_extent_reuse_bounds_device_growth(self, tmp_path):
        # repeated COW overwrites recycle freed extents: the device
        # must not grow linearly with write count
        st = TinStore(str(tmp_path / "s"))
        data = bytes(range(256)) * 64          # 16 KiB
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "a", 0, data))
        for _ in range(16):
            st.queue_transaction(Transaction().write("c", "a", 0, data))
        dev = os.path.getsize(os.path.join(str(tmp_path / "s"),
                                           "block.dev"))
        # steady state: live extent + one COW scratch extent
        assert dev <= 2 * len(data) + 4096, f"device grew to {dev}"
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep["used_bytes"] == 16384 and not rep["extent_errors"]

    def test_remove_returns_space(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        data = bytes(64 << 10)
        t = Transaction().create_collection("c")
        for i in range(4):
            t.write("c", f"o{i}", 0, data)
        st.queue_transaction(t)
        used0 = st._alloc.used_bytes()
        t = Transaction()
        for i in range(4):
            t.remove("c", f"o{i}")
        st.queue_transaction(t)
        assert st._alloc.used_bytes() == 0 and used0 == 4 * (64 << 10)
        # freed space is reused, not appended after
        st.queue_transaction(Transaction().write("c", "n", 0, data))
        assert st._alloc.used_bytes() == 64 << 10
        dev = os.path.getsize(os.path.join(str(tmp_path / "s"),
                                           "block.dev"))
        assert dev <= 4 * (64 << 10)

    def test_derived_allocator_survives_crash(self, tmp_path):
        # allocations are not persisted: after SIGKILL the allocator
        # rebuilds from the extent map and audits cleanly
        st = TinStore(str(tmp_path / "s"))
        rng = np.random.default_rng(3)
        t = Transaction().create_collection("c")
        for i in range(10):
            t.write("c", f"o{i}", 0,
                    rng.integers(0, 256, 5000 + 117 * i,
                                 np.uint8).tobytes())
        st.queue_transaction(t)
        st.queue_transaction(
            Transaction().remove("c", "o3").remove("c", "o7"))
        used = st._alloc.used_bytes()
        st.crash()
        st.remount()
        assert st._alloc.used_bytes() == used
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert not rep["extent_errors"] and rep["used_bytes"] == used

    def test_omap_rmkeys_survive_crash_replay(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c").touch("c", "o")
            .omap_set("c", "o", {b"a": b"1", b"b": b"2"}))
        st.queue_transaction(Transaction().omap_rmkeys("c", "o", [b"a"]))
        st.crash()                 # rmkeys lives only in the WAL tail
        st.remount()
        assert dict(st.collections["c"]["o"].omap) == {b"b": b"2"}


class TestTinStoreCluster:
    """SimCluster on the persistent store: kill really drops RAM."""

    def _mk(self, tmp_path, **kw):
        from ceph_tpu.osd.cluster import SimCluster
        kw.setdefault("down_out_interval", 600.0)
        return SimCluster(n_osds=8, pg_num=4, store="tin",
                          store_dir=str(tmp_path / "osds"), **kw)

    def test_kill_revive_recovers_from_disk(self, tmp_path):
        from ceph_tpu.client.objecter import Objecter
        c = self._mk(tmp_path)
        ob = Objecter(c)
        rng = np.random.default_rng(7)
        objs = {f"obj{i}": rng.integers(0, 256, 500, np.uint8).tobytes()
                for i in range(12)}
        ob.write(objs)
        victim = c.pgs[0].acting[0]
        c.kill_osd(victim)
        # the victim's RAM state is genuinely gone
        with pytest.raises(RuntimeError):
            c.cluster.stores[victim].read("anything", "at-all")
        c.tick(30.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want      # degraded reads
        c.revive_osd(victim)                            # WAL remount
        c.tick(30.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        for ps in range(c.pg_num):
            rep = c.pgs[ps].deep_scrub(dead_osds=c._dead_osds())
            assert rep["inconsistent"] == []

    def test_writes_while_down_replay_onto_revived_store(self, tmp_path):
        from ceph_tpu.client.objecter import Objecter
        c = self._mk(tmp_path)
        ob = Objecter(c)
        rng = np.random.default_rng(8)
        first = {f"a{i}": rng.integers(0, 256, 300, np.uint8).tobytes()
                 for i in range(6)}
        ob.write(first)
        victim = c.pgs[0].acting[1]
        c.kill_osd(victim)
        c.tick(30.0)
        second = {f"b{i}": rng.integers(0, 256, 300, np.uint8).tobytes()
                  for i in range(6)}
        ob.write(second)                 # lands degraded
        c.revive_osd(victim)             # delta replay catches the shard up
        c.tick(30.0)
        for name, want in {**first, **second}.items():
            assert ob.read(name).tobytes() == want
        # and the catch-up is durable: kill + remount again, re-verify
        c.kill_osd(victim)
        c.revive_osd(victim)
        c.tick(30.0)
        for name, want in {**first, **second}.items():
            assert ob.read(name).tobytes() == want

    def test_destroy_removes_disk_and_rebuild_lands_elsewhere(
            self, tmp_path):
        from ceph_tpu.client.objecter import Objecter
        c = self._mk(tmp_path, down_out_interval=30.0)
        ob = Objecter(c)
        rng = np.random.default_rng(9)
        objs = {f"o{i}": rng.integers(0, 256, 400, np.uint8).tobytes()
                for i in range(10)}
        ob.write(objs)
        victim = c.pgs[0].acting[0]
        vdir = os.path.join(c.store_dir, f"osd.{victim}")
        assert os.path.isdir(vdir)
        c.destroy_osd(victim)
        assert not os.path.exists(vdir)  # disk files really deleted
        c.tick(40.0)                     # down -> out -> re-place
        for _ in range(120):
            if not c.backfills:
                break
            c.tick(6.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want


class TestTinStoreCompression:
    """Inline compression (ref: BlueStore bluestore_compression_*
    decision + per-blob compressed_length; csum over stored bytes)."""

    def _mk(self, tmp_path, **kw):
        kw.setdefault("compression", "zlib")
        return TinStore(str(tmp_path / "tc"), **kw)

    def test_compressible_shrinks_device_usage(self, tmp_path):
        st = self._mk(tmp_path)
        data = b"ABCD" * 64 * 1024                    # 256 KiB, ratio ~0
        st.queue_transaction(Transaction().create_collection("c")
                             .write("c", "o", 0, data))
        assert bytes(st.read("c", "o")) == data
        s = st.compress_stats
        assert s["compressed_blobs"] == 1
        assert s["stored_bytes"] < len(data) // 10
        # the extent map footprint matches the compressed size
        o = st._meta["c"]["o"]
        assert o.calg == "zlib" and o.clen < len(data) // 10
        assert o.size == len(data)                    # logical size kept

    def test_incompressible_stays_raw(self, tmp_path):
        st = self._mk(tmp_path)
        rng = np.random.default_rng(9)
        data = rng.integers(0, 256, 64 * 1024, np.uint8).tobytes()
        st.queue_transaction(Transaction().create_collection("c")
                             .write("c", "o", 0, data))
        o = st._meta["c"]["o"]
        assert o.calg == "" and st.compress_stats["raw_blobs"] >= 1
        assert bytes(st.read("c", "o")) == data

    def test_below_min_blob_stays_raw(self, tmp_path):
        st = self._mk(tmp_path, compression_min_blob=4096)
        st.queue_transaction(Transaction().create_collection("c")
                             .write("c", "o", 0, b"A" * 1000))
        assert st._meta["c"]["o"].calg == ""

    def test_crash_remount_preserves_compressed_objects(self, tmp_path):
        st = self._mk(tmp_path)
        data = bytes(range(256)) * 2048               # 512 KiB
        st.queue_transaction(Transaction().create_collection("c")
                             .write("c", "o", 0, data))
        st.crash()
        st.remount()
        assert bytes(st.read("c", "o")) == data
        assert st._meta["c"]["o"].calg == "zlib"      # WAL replay kept it
        # and across a checkpoint cycle too
        st.checkpoint()
        st.crash()
        st.remount()
        assert bytes(st.read("c", "o")) == data
        assert st._meta["c"]["o"].calg == "zlib"

    def test_poke_compressed_stream_detected(self, tmp_path):
        st = self._mk(tmp_path)
        data = b"payload " * 32 * 1024
        st.queue_transaction(Transaction().create_collection("c")
                             .write("c", "o", 0, data))
        view = st.collections["c"]["o"].data
        assert len(view) == st._meta["c"]["o"].clen   # stored stream
        view[len(view) // 2] ^= 0xFF
        view.flush()
        with pytest.raises(TinStoreCorruption):
            st.read("c", "o")
        # fsck sees the same damage offline
        st.umount()
        rep = TinStore.fsck(str(tmp_path / "tc"))
        assert rep["bad_objects"] == ["c/o"]

    def test_lzma_roundtrip(self, tmp_path):
        st = self._mk(tmp_path, compression="lzma")
        data = b"lzma lane " * 20000
        st.queue_transaction(Transaction().create_collection("c")
                             .write("c", "o", 0, data))
        assert st._meta["c"]["o"].calg == "lzma"
        st.crash(); st.remount()
        assert bytes(st.read("c", "o")) == data

    def test_bad_alg_refused(self, tmp_path):
        with pytest.raises(ValueError, match="unknown compression"):
            TinStore(str(tmp_path / "x"), compression="snappy")

    def test_compressed_cluster_kill_revive(self, tmp_path):
        """The whole EC/recovery pipeline over COMPRESSED stores:
        shard bytes (highly compressible corpus) survive SIGKILL +
        WAL remount, decompressing bit-exact through degraded reads
        and deep scrub."""
        from ceph_tpu.client.objecter import Objecter
        from ceph_tpu.osd.cluster import SimCluster
        c = SimCluster(n_osds=8, pg_num=4, store="tin",
                       store_dir=str(tmp_path / "osds"),
                       store_compression="zlib",
                       down_out_interval=600.0)
        ob = Objecter(c)
        objs = {f"cz{i}": (f"block {i} " * 600).encode()
                for i in range(10)}
        ob.write(objs)
        assert any(st.compress_stats["compressed_blobs"] > 0
                   for st in c.cluster.stores.values())
        victim = c.pgs[0].acting[0]
        c.kill_osd(victim)
        c.tick(30.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        c.revive_osd(victim)
        c.tick(30.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        for ps in range(c.pg_num):
            rep = c.pgs[ps].deep_scrub(dead_osds=c._dead_osds())
            assert rep["inconsistent"] == []


class TestLegacyForwardReplay:
    """Pre-KV stores (v3 `ckpt` checkpoint + metadata-op WAL) must
    mount on the KV TinStore: migration forward-replays them into
    TinDB's first segment and lands the MANIFEST atomically. Nothing
    readable before may be unreadable after."""

    def _make_legacy(self, path):
        """Fabricate a pre-KV store directly in the legacy on-disk
        format: sealed v3 checkpoint + two metadata-op WAL records."""
        from ceph_tpu.kv.tindb import append_wal_record, host_crc32c
        from ceph_tpu.osd.tinstore import (_encode_meta_txn,
                                           ExtentAllocator)
        from ceph_tpu.utils.encoding import Encoder
        os.makedirs(path, exist_ok=True)
        payloads = {"o1": b"legacy object one", "o2": b"second" * 40}
        doffs = {}
        with open(os.path.join(path, "block.dev"), "wb") as dev:
            off = 0
            for oid, data in payloads.items():
                dev.write(data)
                doffs[oid] = (off, ExtentAllocator.round_up(len(data)))
                pad = doffs[oid][1] - len(data)
                dev.write(b"\x00" * pad)
                off += doffs[oid][1]
        e = Encoder()
        e.start(3, 3)
        e.u64(0)                      # base_seq
        e.u64(1)                      # committed_txns at checkpoint
        e.u32(1)                      # one collection
        e.string("c")
        e.u32(len(payloads))
        from ceph_tpu.osd.tinstore import _crc32c
        for oid, data in payloads.items():
            doff, dlen = doffs[oid]
            e.string(oid)
            e.u64(len(data)).u64(doff).u64(dlen).u32(_crc32c(data))
            e.mapping({"who": b"ckpt"}, Encoder.string, Encoder.blob)
            e.mapping({b"ck": b"from-ckpt"} if oid == "o1" else {},
                      Encoder.blob, Encoder.blob)
            e.string("").u64(0).u32(0)      # uncompressed
        e.finish()
        body = e.bytes()
        body += struct.pack("<I", host_crc32c(body))
        with open(os.path.join(path, "ckpt"), "wb") as f:
            f.write(body)
        with open(os.path.join(path, "wal.log"), "wb") as f:
            append_wal_record(f, 1, _encode_meta_txn(
                [("touch", "c", "o3"),
                 ("omap_set", "c", "o1", {b"wk": b"from-wal"})]),
                o_dsync=False)
            append_wal_record(f, 2, _encode_meta_txn(
                [("setattr", "c", "o3", "hinfo", b"\x07")]),
                o_dsync=False)
        return payloads

    def test_legacy_store_migrates_and_serves(self, tmp_path):
        path = str(tmp_path / "old")
        payloads = self._make_legacy(path)
        # pre-migration fsck sees the legacy format, clean
        rep = TinStore.fsck(path)
        assert rep["format"] == "legacy" and not rep["errors"]
        assert not rep["bad_objects"]
        st = TinStore(path)           # mount = forward migration
        assert os.path.exists(os.path.join(path, "MANIFEST"))
        assert not os.path.exists(os.path.join(path, "ckpt"))
        for oid, data in payloads.items():
            assert bytes(st.read("c", oid)) == data
            assert st.getattr("c", oid, "who") == b"ckpt"
        # checkpoint omap AND wal omap both present, ordered
        assert dict(st.collections["c"]["o1"].omap) \
            == {b"ck": b"from-ckpt", b"wk": b"from-wal"}
        assert st.exists("c", "o3")
        assert st.getattr("c", "o3", "hinfo") == b"\x07"
        # ckpt committed 1 txn + 2 wal records
        assert st.committed_txns == 3
        st.umount()
        rep = TinStore.fsck(path)
        assert rep["format"] == "kv" and not rep["errors"]
        assert not rep["bad_objects"] and not rep["extent_errors"]

    def test_migrated_store_is_durable_and_writable(self, tmp_path):
        path = str(tmp_path / "old")
        payloads = self._make_legacy(path)
        st = TinStore(path)
        st.queue_transaction(
            Transaction().write("c", "post", 0, b"post-migration")
            .omap_set("c", "o3", {b"nk": b"nv"}))
        st.crash()
        st.remount()                  # plain KV remount, no re-migration
        for oid, data in payloads.items():
            assert bytes(st.read("c", oid)) == data
        assert bytes(st.read("c", "post")) == b"post-migration"
        assert dict(st.collections["c"]["o3"].omap) == {b"nk": b"nv"}

    def test_crash_before_manifest_reruns_migration(self, tmp_path):
        # the migration's commit point is the MANIFEST rename: fake
        # the "crashed halfway" window (segment written, no MANIFEST)
        # with a stray orphan segment — remount must re-migrate and
        # reclaim the orphan
        path = str(tmp_path / "old")
        payloads = self._make_legacy(path)
        from ceph_tpu.kv.tindb import write_segment
        write_segment(os.path.join(path, "seg-00000001.tdb"),
                      [(b"O\x00half", b"way")])
        st = TinStore(path)           # _is_legacy: no MANIFEST -> migrate
        for oid, data in payloads.items():
            assert bytes(st.read("c", oid)) == data
        assert st._db.get("O", b"half") is None
        st.umount()
        assert not TinStore.fsck(path)["errors"]

    def test_legacy_mid_log_corruption_still_fatal(self, tmp_path):
        path = str(tmp_path / "old")
        self._make_legacy(path)
        with open(os.path.join(path, "wal.log"), "r+b") as f:
            f.seek(20)
            f.write(b"\xff\xff\xff")
        with pytest.raises(TinStoreCorruption):
            TinStore(path)
        rep = TinStore.fsck(path)
        assert rep["format"] == "legacy" and rep["errors"]


def test_store_bench_tool_smoke():
    """tools/store_bench.py (the fio_ceph_objectstore role) runs both
    backends and emits sane JSON."""
    import json
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for extra in (["--store", "mem"], ["--store", "tin"]):
        r = subprocess.run(
            [sys.executable, "tools/store_bench.py", "--seconds", "0.5",
             "--objects", "32", "--object-size", "8192", "--json",
             *extra, "randwrite"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=repo)
        assert r.returncode == 0, r.stderr[-400:]
        d = json.loads(r.stdout.strip().splitlines()[-1])
        assert d["iops"] > 0 and d["mb_per_s"] > 0
        assert d["ops"] >= d["txn_ops"]
