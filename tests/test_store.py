"""ObjectStore suite — ONE contract run against BOTH stores (the
reference's interface parameterization: src/test/objectstore/
store_test.cc runs the same suite over MemStore and BlueStore), plus
TinStore-only durability tests: WAL replay after SIGKILL, torn-tail
truncation, checkpoint cycling, verify-on-read, fsck, and a cluster
kill/revive that REALLY loses RAM (ref: src/os/bluestore/BlueStore.cc
_verify_csum/fsck; qa process-kill thrash semantics)."""

import os
import struct

import numpy as np
import pytest

from ceph_tpu.osd.memstore import MemStore, Transaction
from ceph_tpu.osd.tinstore import TinStore, TinStoreCorruption


@pytest.fixture(params=["mem", "tin"])
def store(request, tmp_path):
    if request.param == "mem":
        yield MemStore()
    else:
        yield TinStore(str(tmp_path / "tin"))


def reopen(st):
    """Persistence boundary: for TinStore simulate SIGKILL + remount;
    for MemStore a no-op (its contract is RAM-lifetime only)."""
    if isinstance(st, TinStore):
        st.crash()
        st.remount()
    return st


class TestStoreContract:
    def test_write_read_roundtrip(self, store):
        t = (Transaction().create_collection("c")
             .write("c", "o", 0, b"hello world"))
        store.queue_transaction(t)
        assert bytes(store.read("c", "o")) == b"hello world"
        assert store.stat("c", "o") == 11

    def test_write_extends_with_zeros(self, store):
        store.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 4, b"xy"))
        assert bytes(store.read("c", "o")) == b"\x00\x00\x00\x00xy"

    def test_overwrite_middle(self, store):
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"abcdef").write("c", "o", 2, b"XY"))
        assert bytes(store.read("c", "o")) == b"abXYef"

    def test_truncate_shrink_and_grow(self, store):
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"abcdef").truncate("c", "o", 3))
        assert bytes(store.read("c", "o")) == b"abc"
        store.queue_transaction(Transaction().truncate("c", "o", 5))
        assert bytes(store.read("c", "o")) == b"abc\x00\x00"

    def test_remove_and_touch(self, store):
        store.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"x").remove("c", "o").touch("c", "p"))
        assert not store.exists("c", "o")
        assert store.exists("c", "p")
        assert store.stat("c", "p") == 0

    def test_xattr_and_omap(self, store):
        store.queue_transaction(
            Transaction().create_collection("c").touch("c", "o")
            .setattr("c", "o", "hinfo", b"\x01\x02")
            .omap_set("c", "o", {b"k": b"v"}))
        assert store.getattr("c", "o", "hinfo") == b"\x01\x02"
        store.queue_transaction(Transaction().rmattr("c", "o", "hinfo"))
        with pytest.raises(KeyError):
            store.getattr("c", "o", "hinfo")

    def test_collections_listing(self, store):
        store.queue_transaction(
            Transaction().create_collection("b").create_collection("a")
            .write("a", "z", 0, b"1").write("a", "y", 0, b"2"))
        assert store.list_collections() == ["a", "b"]
        assert store.list_objects("a") == ["y", "z"]
        store.queue_transaction(Transaction().remove_collection("b"))
        assert store.list_collections() == ["a"]

    def test_validation_aborts_whole_txn(self, store):
        store.queue_transaction(Transaction().create_collection("c"))
        bad = (Transaction().write("c", "o", 0, b"data")
               .write("nope", "o", 0, b"data"))
        with pytest.raises(KeyError):
            store.queue_transaction(bad)
        # all-or-nothing: the eligible first op must NOT have applied
        assert not store.exists("c", "o")

    def test_missing_reads_raise(self, store):
        with pytest.raises(KeyError):
            store.read("c", "o")
        store.queue_transaction(Transaction().create_collection("c"))
        with pytest.raises(KeyError):
            store.read("c", "o")


class TestTinStoreDurability:
    def test_kill_loses_nothing_committed(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"committed bytes")
            .setattr("c", "o", "a", b"xattr")
            .omap_set("c", "o", {b"k": b"v"}))
        st.crash()                      # SIGKILL: RAM gone
        with pytest.raises(RuntimeError):
            st.read("c", "o")
        st.remount()                    # recovery = WAL replay only
        assert bytes(st.read("c", "o")) == b"committed bytes"
        assert st.getattr("c", "o", "a") == b"xattr"
        assert st.committed_txns == 1

    def test_many_txns_replay_in_order(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(Transaction().create_collection("c"))
        rng = np.random.default_rng(3)
        want = {}
        for i in range(40):
            data = rng.integers(0, 256, int(rng.integers(1, 400)),
                                np.uint8)
            name = f"o{i % 7}"         # overwrites interleave creates
            st.queue_transaction(
                Transaction().write("c", name, 0, data)
                .truncate("c", name, len(data)))
            want[name] = data.tobytes()
        reopen(st)
        for name, data in want.items():
            assert bytes(st.read("c", name)) == data

    def test_torn_tail_record_dropped(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 0, b"ok"))
        st.crash()
        # simulate crash mid-append: garbage half-record at the tail
        with open(os.path.join(str(tmp_path / "s"), "wal.log"), "ab") as f:
            f.write(struct.pack("<IQI", 0x544E4952, 99, 1 << 20))
            f.write(b"\x01\x02\x03")    # body cut short
        st.remount()
        assert bytes(st.read("c", "o")) == b"ok"
        # the torn bytes were truncated away; new commits extend cleanly
        st.queue_transaction(Transaction().write("c", "p", 0, b"post"))
        reopen(st)
        assert bytes(st.read("c", "p")) == b"post"

    def test_mid_log_corruption_fails_loudly(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c").write("c", "a", 0, b"1"))
        st.queue_transaction(Transaction().write("c", "b", 0, b"2"))
        st.crash()
        wal = os.path.join(str(tmp_path / "s"), "wal.log")
        with open(wal, "r+b") as f:
            f.seek(20)                  # inside record 1's body
            f.write(b"\xff\xff")
        with pytest.raises(TinStoreCorruption):
            st.remount()
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep["errors"]

    def test_checkpoint_cycle_and_recovery(self, tmp_path):
        st = TinStore(str(tmp_path / "s"), wal_max_bytes=2000)
        st.queue_transaction(Transaction().create_collection("c"))
        rng = np.random.default_rng(5)
        want = {}
        for i in range(30):             # crosses several checkpoints
            data = rng.integers(0, 256, 150, np.uint8)
            st.queue_transaction(Transaction().write("c", f"o{i}", 0, data))
            want[f"o{i}"] = data.tobytes()
        assert os.path.exists(os.path.join(str(tmp_path / "s"), "ckpt"))
        reopen(st)
        for name, data in want.items():
            assert bytes(st.read("c", name)) == data
        assert st.committed_txns == 31

    def test_umount_checkpoint_then_clean_mount(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 0, b"z"))
        st.umount()
        # after umount the WAL is empty; state lives in the checkpoint
        assert os.path.getsize(
            os.path.join(str(tmp_path / "s"), "wal.log")) == 0
        st.remount()
        assert bytes(st.read("c", "o")) == b"z"

    def test_verify_on_read_catches_ram_rot(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"clean bytes"))
        st.collections["c"]["o"].data[3] ^= 0x40    # bypasses the WAL
        with pytest.raises(TinStoreCorruption):
            st.read("c", "o")

    def test_checkpoint_corruption_detected_at_mount(self, tmp_path):
        st = TinStore(str(tmp_path / "s"))
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o", 0, b"will be sealed"))
        st.umount()
        ckpt = os.path.join(str(tmp_path / "s"), "ckpt")
        with open(ckpt, "r+b") as f:
            f.seek(30)
            f.write(b"\xaa")
        with pytest.raises(TinStoreCorruption):
            st.remount()
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep["errors"]

    def test_fsck_clean_report(self, tmp_path):
        st = TinStore(str(tmp_path / "s"), wal_max_bytes=10 << 20)
        st.queue_transaction(
            Transaction().create_collection("c")
            .write("c", "o1", 0, b"abc").write("c", "o2", 0, b"def"))
        st.queue_transaction(Transaction().write("c", "o3", 0, b"ghi"))
        st.crash()
        rep = TinStore.fsck(str(tmp_path / "s"))
        assert rep == {"objects": 3, "bad_objects": [],
                       "wal_records": 2, "torn_tail": False,
                       "errors": []}


class TestTinStoreCluster:
    """SimCluster on the persistent store: kill really drops RAM."""

    def _mk(self, tmp_path, **kw):
        from ceph_tpu.osd.cluster import SimCluster
        kw.setdefault("down_out_interval", 600.0)
        return SimCluster(n_osds=8, pg_num=4, store="tin",
                          store_dir=str(tmp_path / "osds"), **kw)

    def test_kill_revive_recovers_from_disk(self, tmp_path):
        from ceph_tpu.client.objecter import Objecter
        c = self._mk(tmp_path)
        ob = Objecter(c)
        rng = np.random.default_rng(7)
        objs = {f"obj{i}": rng.integers(0, 256, 500, np.uint8).tobytes()
                for i in range(12)}
        ob.write(objs)
        victim = c.pgs[0].acting[0]
        c.kill_osd(victim)
        # the victim's RAM state is genuinely gone
        with pytest.raises(RuntimeError):
            c.cluster.stores[victim].read("anything", "at-all")
        c.tick(30.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want      # degraded reads
        c.revive_osd(victim)                            # WAL remount
        c.tick(30.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
        for ps in range(c.pg_num):
            rep = c.pgs[ps].deep_scrub(dead_osds=c._dead_osds())
            assert rep["inconsistent"] == []

    def test_writes_while_down_replay_onto_revived_store(self, tmp_path):
        from ceph_tpu.client.objecter import Objecter
        c = self._mk(tmp_path)
        ob = Objecter(c)
        rng = np.random.default_rng(8)
        first = {f"a{i}": rng.integers(0, 256, 300, np.uint8).tobytes()
                 for i in range(6)}
        ob.write(first)
        victim = c.pgs[0].acting[1]
        c.kill_osd(victim)
        c.tick(30.0)
        second = {f"b{i}": rng.integers(0, 256, 300, np.uint8).tobytes()
                  for i in range(6)}
        ob.write(second)                 # lands degraded
        c.revive_osd(victim)             # delta replay catches the shard up
        c.tick(30.0)
        for name, want in {**first, **second}.items():
            assert ob.read(name).tobytes() == want
        # and the catch-up is durable: kill + remount again, re-verify
        c.kill_osd(victim)
        c.revive_osd(victim)
        c.tick(30.0)
        for name, want in {**first, **second}.items():
            assert ob.read(name).tobytes() == want

    def test_destroy_removes_disk_and_rebuild_lands_elsewhere(
            self, tmp_path):
        from ceph_tpu.client.objecter import Objecter
        c = self._mk(tmp_path, down_out_interval=30.0)
        ob = Objecter(c)
        rng = np.random.default_rng(9)
        objs = {f"o{i}": rng.integers(0, 256, 400, np.uint8).tobytes()
                for i in range(10)}
        ob.write(objs)
        victim = c.pgs[0].acting[0]
        vdir = os.path.join(c.store_dir, f"osd.{victim}")
        assert os.path.isdir(vdir)
        c.destroy_osd(victim)
        assert not os.path.exists(vdir)  # disk files really deleted
        c.tick(40.0)                     # down -> out -> re-place
        for _ in range(120):
            if not c.backfills:
                break
            c.tick(6.0)
        for name, want in objs.items():
            assert ob.read(name).tobytes() == want
