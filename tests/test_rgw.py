"""RGW-lite gateway tests (refs: src/rgw/rgw_op.cc PutObj/GetObj/
DeleteObj/ListBucket; cls/rgw bucket index; rgw_multi.cc multipart).
The gateway rides librados + striper, so EC fan-out, COW snapshots,
and recovery apply to S3 data with no special cases — the failure
test proves it end-to-end."""

import numpy as np
import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.osd.cluster import SimCluster
from ceph_tpu.rgw import Gateway, GatewayError, NoSuchBucket, NoSuchKey


def mk(**kw):
    kw.setdefault("n_osds", 8)
    kw.setdefault("pg_num", 4)
    c = SimCluster(**kw)
    return c, Gateway(Rados(c).open_ioctx())


class TestBuckets:
    def test_create_list_delete(self):
        c, gw = mk()
        gw.create_bucket("alpha")
        gw.create_bucket("beta")
        assert gw.list_buckets() == ["alpha", "beta"]
        gw.delete_bucket("alpha")
        assert gw.list_buckets() == ["beta"]

    def test_duplicate_and_missing(self):
        c, gw = mk()
        gw.create_bucket("b")
        with pytest.raises(GatewayError, match="BucketAlreadyExists"):
            gw.create_bucket("b")
        with pytest.raises(NoSuchBucket):
            gw.put_object("nope", "k", b"x")
        with pytest.raises(GatewayError, match="bad bucket"):
            gw.create_bucket("a/b")

    def test_delete_nonempty_refused(self):
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"x")
        with pytest.raises(GatewayError, match="BucketNotEmpty"):
            gw.delete_bucket("b")
        gw.delete_object("b", "k")
        gw.delete_bucket("b")


class TestObjects:
    def test_put_get_head_delete_roundtrip(self):
        c, gw = mk()
        gw.create_bucket("b")
        rng = np.random.default_rng(1)
        data = rng.integers(0, 256, 5000, np.uint8).tobytes()
        etag = gw.put_object("b", "docs/a.bin", data)
        assert gw.get_object("b", "docs/a.bin") == data
        head = gw.head_object("b", "docs/a.bin")
        assert head["size"] == 5000 and head["etag"] == etag
        gw.delete_object("b", "docs/a.bin")
        with pytest.raises(NoSuchKey):
            gw.get_object("b", "docs/a.bin")

    def test_overwrite_shrinks_cleanly(self):
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"A" * 100_000)   # multi-stripe
        gw.put_object("b", "k", b"short")
        assert gw.get_object("b", "k") == b"short"

    def test_range_get(self):
        c, gw = mk()
        gw.create_bucket("b")
        rng = np.random.default_rng(2)
        data = rng.integers(0, 256, 200_000, np.uint8).tobytes()
        gw.put_object("b", "big", data)           # stripes + objects
        assert gw.get_object("b", "big", offset=65_000,
                             length=1000) == data[65_000:66_000]

    def test_list_prefix_and_pagination(self):
        c, gw = mk()
        gw.create_bucket("b")
        for i in range(10):
            gw.put_object("b", f"logs/{i:02d}", b"x")
        gw.put_object("b", "other", b"y")
        out = gw.list_objects("b", prefix="logs/", limit=4)
        assert [e["key"] for e in out["entries"]] == \
            ["logs/00", "logs/01", "logs/02", "logs/03"]
        assert out["truncated"]
        out2 = gw.list_objects("b", prefix="logs/",
                               marker=out["next_marker"], limit=100)
        assert [e["key"] for e in out2["entries"]] == \
            [f"logs/{i:02d}" for i in range(4, 10)]
        assert not out2["truncated"]

    def test_data_survives_osd_failure(self):
        c, gw = mk(down_out_interval=30.0)
        gw.create_bucket("b")
        rng = np.random.default_rng(3)
        blobs = {f"k{i}": rng.integers(0, 256, 30_000,
                                       np.uint8).tobytes()
                 for i in range(6)}
        for k, v in blobs.items():
            gw.put_object("b", k, v)
        c.kill_osd(c.pgs[0].acting[0])
        c.tick(40.0)
        for _ in range(120):
            if not c.backfills:
                break
            c.tick(6.0)
        for k, v in blobs.items():
            assert gw.get_object("b", k) == v
        assert len(gw.list_objects("b")["entries"]) == 6


class TestMultipart:
    def test_multipart_roundtrip(self):
        c, gw = mk()
        gw.create_bucket("b")
        rng = np.random.default_rng(4)
        parts = [rng.integers(0, 256, 70_000, np.uint8).tobytes()
                 for _ in range(3)]
        uid = gw.initiate_multipart("b", "assembled")
        for i, p in enumerate(parts, start=1):
            gw.upload_part("b", "assembled", uid, i, p)
        etag = gw.complete_multipart("b", "assembled", uid)
        assert etag.endswith("-3")
        whole = b"".join(parts)
        assert gw.get_object("b", "assembled") == whole
        assert gw.head_object("b", "assembled")["size"] == len(whole)
        # range read across a part boundary
        assert gw.get_object("b", "assembled", offset=69_000,
                             length=2000) == whole[69_000:71_000]
        gw.delete_object("b", "assembled")
        with pytest.raises(NoSuchKey):
            gw.get_object("b", "assembled")

    def test_abort_cleans_parts(self):
        c, gw = mk()
        gw.create_bucket("b")
        uid = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", uid, 1, b"p" * 10_000)
        gw.abort_multipart("b", "k", uid)
        with pytest.raises(GatewayError, match="NoSuchUpload"):
            gw.upload_part("b", "k", uid, 2, b"q")
        with pytest.raises(NoSuchKey):
            gw.get_object("b", "k")

    def test_put_over_multipart_wipes_parts(self):
        # r3 advisory: a plain PUT replacing a multipart object must
        # wipe the manifest's part objects or they orphan forever
        c, gw = mk()
        gw.create_bucket("b")
        uid = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", uid, 1, b"x" * 50_000)
        gw.upload_part("b", "k", uid, 2, b"y" * 50_000)
        gw.complete_multipart("b", "k", uid)
        parts = gw.head_object("b", "k")["manifest"]
        assert parts
        gw.put_object("b", "k", b"small replacement")
        assert gw.get_object("b", "k") == b"small replacement"
        for soid in parts:
            with pytest.raises(KeyError):
                gw._striper.read(soid, length=1)

    def test_complete_over_existing_objects_wipes_old(self):
        # complete_multipart replaces the index entry exactly like
        # put_object does — a previous upload's parts and a previous
        # plain object's data must not orphan (r4 review)
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"plain " * 5_000)
        plain_soid = gw._data_obj("b", "k")
        u1 = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", u1, 1, b"a" * 60_000)
        gw.complete_multipart("b", "k", u1)
        parts1 = gw.head_object("b", "k")["manifest"]
        with pytest.raises(KeyError):
            gw._striper.read(plain_soid, length=1)   # plain data wiped
        u2 = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", u2, 1, b"b" * 60_000)
        gw.complete_multipart("b", "k", u2)
        for soid in parts1:                           # u1 parts wiped
            with pytest.raises(KeyError):
                gw._striper.read(soid, length=1)
        assert gw.get_object("b", "k") == b"b" * 60_000

    def test_unknown_upload_refused(self):
        c, gw = mk()
        gw.create_bucket("b")
        with pytest.raises(GatewayError, match="NoSuchUpload"):
            gw.complete_multipart("b", "k", "u0000000000000000")
