"""RGW-lite gateway tests (refs: src/rgw/rgw_op.cc PutObj/GetObj/
DeleteObj/ListBucket; cls/rgw bucket index; rgw_multi.cc multipart).
The gateway rides librados + striper, so EC fan-out, COW snapshots,
and recovery apply to S3 data with no special cases — the failure
test proves it end-to-end."""

import numpy as np
import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.osd.cluster import SimCluster
from ceph_tpu.rgw import Gateway, GatewayError, NoSuchBucket, NoSuchKey


def mk(**kw):
    kw.setdefault("n_osds", 8)
    kw.setdefault("pg_num", 4)
    c = SimCluster(**kw)
    return c, Gateway(Rados(c).open_ioctx())


class TestBuckets:
    def test_create_list_delete(self):
        c, gw = mk()
        gw.create_bucket("alpha")
        gw.create_bucket("beta")
        assert gw.list_buckets() == ["alpha", "beta"]
        gw.delete_bucket("alpha")
        assert gw.list_buckets() == ["beta"]

    def test_duplicate_and_missing(self):
        c, gw = mk()
        gw.create_bucket("b")
        with pytest.raises(GatewayError, match="BucketAlreadyExists"):
            gw.create_bucket("b")
        with pytest.raises(NoSuchBucket):
            gw.put_object("nope", "k", b"x")
        with pytest.raises(GatewayError, match="bad bucket"):
            gw.create_bucket("a/b")

    def test_delete_nonempty_refused(self):
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"x")
        with pytest.raises(GatewayError, match="BucketNotEmpty"):
            gw.delete_bucket("b")
        gw.delete_object("b", "k")
        gw.delete_bucket("b")


class TestObjects:
    def test_put_get_head_delete_roundtrip(self):
        c, gw = mk()
        gw.create_bucket("b")
        rng = np.random.default_rng(1)
        data = rng.integers(0, 256, 5000, np.uint8).tobytes()
        etag = gw.put_object("b", "docs/a.bin", data)
        assert gw.get_object("b", "docs/a.bin") == data
        head = gw.head_object("b", "docs/a.bin")
        assert head["size"] == 5000 and head["etag"] == etag
        gw.delete_object("b", "docs/a.bin")
        with pytest.raises(NoSuchKey):
            gw.get_object("b", "docs/a.bin")

    def test_overwrite_shrinks_cleanly(self):
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"A" * 100_000)   # multi-stripe
        gw.put_object("b", "k", b"short")
        assert gw.get_object("b", "k") == b"short"

    def test_range_get(self):
        c, gw = mk()
        gw.create_bucket("b")
        rng = np.random.default_rng(2)
        data = rng.integers(0, 256, 200_000, np.uint8).tobytes()
        gw.put_object("b", "big", data)           # stripes + objects
        assert gw.get_object("b", "big", offset=65_000,
                             length=1000) == data[65_000:66_000]

    def test_list_prefix_and_pagination(self):
        c, gw = mk()
        gw.create_bucket("b")
        for i in range(10):
            gw.put_object("b", f"logs/{i:02d}", b"x")
        gw.put_object("b", "other", b"y")
        out = gw.list_objects("b", prefix="logs/", limit=4)
        assert [e["key"] for e in out["entries"]] == \
            ["logs/00", "logs/01", "logs/02", "logs/03"]
        assert out["truncated"]
        out2 = gw.list_objects("b", prefix="logs/",
                               marker=out["next_marker"], limit=100)
        assert [e["key"] for e in out2["entries"]] == \
            [f"logs/{i:02d}" for i in range(4, 10)]
        assert not out2["truncated"]

    def test_data_survives_osd_failure(self):
        c, gw = mk(down_out_interval=30.0)
        gw.create_bucket("b")
        rng = np.random.default_rng(3)
        blobs = {f"k{i}": rng.integers(0, 256, 30_000,
                                       np.uint8).tobytes()
                 for i in range(6)}
        for k, v in blobs.items():
            gw.put_object("b", k, v)
        c.kill_osd(c.pgs[0].acting[0])
        c.tick(40.0)
        for _ in range(120):
            if not c.backfills:
                break
            c.tick(6.0)
        for k, v in blobs.items():
            assert gw.get_object("b", k) == v
        assert len(gw.list_objects("b")["entries"]) == 6


class TestMultipart:
    def test_multipart_roundtrip(self):
        c, gw = mk()
        gw.create_bucket("b")
        rng = np.random.default_rng(4)
        parts = [rng.integers(0, 256, 70_000, np.uint8).tobytes()
                 for _ in range(3)]
        uid = gw.initiate_multipart("b", "assembled")
        for i, p in enumerate(parts, start=1):
            gw.upload_part("b", "assembled", uid, i, p)
        etag = gw.complete_multipart("b", "assembled", uid)
        assert etag.endswith("-3")
        whole = b"".join(parts)
        assert gw.get_object("b", "assembled") == whole
        assert gw.head_object("b", "assembled")["size"] == len(whole)
        # range read across a part boundary
        assert gw.get_object("b", "assembled", offset=69_000,
                             length=2000) == whole[69_000:71_000]
        gw.delete_object("b", "assembled")
        with pytest.raises(NoSuchKey):
            gw.get_object("b", "assembled")

    def test_abort_cleans_parts(self):
        c, gw = mk()
        gw.create_bucket("b")
        uid = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", uid, 1, b"p" * 10_000)
        gw.abort_multipart("b", "k", uid)
        with pytest.raises(GatewayError, match="NoSuchUpload"):
            gw.upload_part("b", "k", uid, 2, b"q")
        with pytest.raises(NoSuchKey):
            gw.get_object("b", "k")

    def test_put_over_multipart_wipes_parts(self):
        # r3 advisory: a plain PUT replacing a multipart object must
        # wipe the manifest's part objects or they orphan forever
        c, gw = mk()
        gw.create_bucket("b")
        uid = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", uid, 1, b"x" * 50_000)
        gw.upload_part("b", "k", uid, 2, b"y" * 50_000)
        gw.complete_multipart("b", "k", uid)
        parts = gw.head_object("b", "k")["manifest"]
        assert parts
        gw.put_object("b", "k", b"small replacement")
        assert gw.get_object("b", "k") == b"small replacement"
        for soid in parts:
            with pytest.raises(KeyError):
                gw._striper.read(soid, length=1)

    def test_complete_over_existing_objects_wipes_old(self):
        # complete_multipart replaces the index entry exactly like
        # put_object does — a previous upload's parts and a previous
        # plain object's data must not orphan (r4 review)
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"plain " * 5_000)
        plain_soid = gw._data_obj("b", "k")
        u1 = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", u1, 1, b"a" * 60_000)
        gw.complete_multipart("b", "k", u1)
        parts1 = gw.head_object("b", "k")["manifest"]
        with pytest.raises(KeyError):
            gw._striper.read(plain_soid, length=1)   # plain data wiped
        u2 = gw.initiate_multipart("b", "k")
        gw.upload_part("b", "k", u2, 1, b"b" * 60_000)
        gw.complete_multipart("b", "k", u2)
        for soid in parts1:                           # u1 parts wiped
            with pytest.raises(KeyError):
                gw._striper.read(soid, length=1)
        assert gw.get_object("b", "k") == b"b" * 60_000

    def test_unknown_upload_refused(self):
        c, gw = mk()
        gw.create_bucket("b")
        with pytest.raises(GatewayError, match="NoSuchUpload"):
            gw.complete_multipart("b", "k", "u0000000000000000")


class TestS3Auth:
    """S3 request authentication (ref: src/rgw/rgw_auth_s3.cc AWSv4
    canonical request + signing-key chain + skew grace): signed
    round-trips, every rejection mode, and replay."""

    def _authed(self, clock=None):
        import time as _t
        c, gw = mk()
        from ceph_tpu.rgw import AuthedGateway, S3Client, UserStore
        users = UserStore()
        access, secret = users.create_user("alice")
        agw = AuthedGateway(gw, users, clock=clock or _t.time)
        return c, agw, S3Client(agw, access, secret,
                                clock=clock or _t.time), (access, secret)

    def test_signed_roundtrip_full_surface(self):
        c, agw, s3, _ = self._authed()
        s3.create_bucket("b")
        etag = s3.put_object("b", "k", b"hello s3 auth" * 100)
        assert s3.get_object("b", "k") == b"hello s3 auth" * 100
        assert s3.head_object("b", "k")["etag"] == etag
        assert [e["key"] for e in s3.list_objects("b")["entries"]] \
            == ["k"]
        # ranged GET rides signed params
        assert s3.get_object("b", "k", offset=6, length=2) == b"s3"
        # multipart, signed end to end
        uid = s3.initiate_multipart("b", "big")
        s3.upload_part("b", "big", uid, 1, b"A" * 70000)
        s3.upload_part("b", "big", uid, 2, b"B" * 50000)
        s3.complete_multipart("b", "big", uid)
        got = s3.get_object("b", "big")
        assert got == b"A" * 70000 + b"B" * 50000
        s3.delete_object("b", "big")
        s3.delete_object("b", "k")
        s3.delete_bucket("b")
        assert s3.list_buckets() == []

    def test_wrong_secret_rejected(self):
        from ceph_tpu.rgw import S3Client, SignatureDoesNotMatch
        c, agw, s3, (access, secret) = self._authed()
        s3.create_bucket("b")
        evil = S3Client(agw, access, "not-the-secret")
        with pytest.raises(SignatureDoesNotMatch):
            evil.put_object("b", "k", b"forged")

    def test_unknown_access_key_rejected(self):
        from ceph_tpu.rgw import AccessDenied, S3Client
        c, agw, s3, _ = self._authed()
        ghost = S3Client(agw, "AKDOESNOTEXIST", "whatever")
        with pytest.raises(AccessDenied, match="InvalidAccessKeyId"):
            ghost.list_buckets()

    def test_clock_skew_rejected_before_signature_math(self):
        import time as _t
        from ceph_tpu.rgw import RequestTimeTooSkewed, S3Client
        c, agw, s3, (access, secret) = self._authed()
        drifted = S3Client(agw, access, secret,
                           clock=lambda: _t.time() - 1200.0)
        with pytest.raises(RequestTimeTooSkewed):
            drifted.list_buckets()

    def test_replay_rejected(self):
        import time as _t
        from ceph_tpu.rgw import AccessDenied
        from ceph_tpu.rgw.auth import amz_date, sign
        c, agw, s3, (access, secret) = self._authed()
        s3.create_bucket("b")
        # capture one signed request verbatim, then re-send it
        date = amz_date(_t.time())
        nonce = "cafecafecafecafe"
        sig = sign(secret, date, "put_object", "b", "k", nonce, {},
                   b"pay once")
        agw.call(access, date, sig, "put_object", bucket="b", key="k",
                 nonce=nonce, payload=b"pay once")
        with pytest.raises(AccessDenied, match="replay"):
            agw.call(access, date, sig, "put_object", bucket="b",
                     key="k", nonce=nonce, payload=b"pay once")
        # but the SAME logical op with a fresh nonce signs differently
        # and goes through (a legit duplicate isn't a replay)
        s3.put_object("b", "k", b"pay once")

    def test_tampered_params_break_the_signature(self):
        import time as _t
        from ceph_tpu.rgw import SignatureDoesNotMatch
        from ceph_tpu.rgw.auth import amz_date, sign
        c, agw, s3, (access, secret) = self._authed()
        s3.create_bucket("b")
        s3.put_object("b", "secret-doc", b"classified")
        date = amz_date(_t.time())
        sig = sign(secret, date, "get_object", "b", "public-doc",
                   "n0", {}, b"")
        # swap the signed key for another: signature must not cover it
        with pytest.raises(SignatureDoesNotMatch):
            agw.call(access, date, sig, "get_object", bucket="b",
                     key="secret-doc", nonce="n0")
        # swap the OP with everything else intact: also rejected
        with pytest.raises(SignatureDoesNotMatch):
            agw.call(access, date, sig, "delete_object", bucket="b",
                     key="public-doc", nonce="n0")

    def test_cross_user_bucket_isolation(self):
        from ceph_tpu.rgw import AccessDenied, S3Client
        c, agw, alice, _ = self._authed()
        bob_ak, bob_sk = agw._users.create_user("bob")
        bob = S3Client(agw, bob_ak, bob_sk)
        alice.create_bucket("alices")
        alice.put_object("alices", "doc", b"hers")
        # bob's signature is valid under HIS key — but the bucket
        # belongs to alice: authorization must refuse every op
        for attempt in (
                lambda: bob.get_object("alices", "doc"),
                lambda: bob.put_object("alices", "doc", b"overwrite"),
                lambda: bob.delete_object("alices", "doc"),
                lambda: bob.delete_bucket("alices"),
                lambda: bob.list_objects("alices")):
            with pytest.raises(AccessDenied, match="another user"):
                attempt()
        # and alice's bucket doesn't leak into bob's listing
        bob.create_bucket("bobs")
        assert bob.list_buckets() == ["bobs"]
        assert alice.list_buckets() == ["alices"]
        assert alice.get_object("alices", "doc") == b"hers"


class TestVersioning:
    """S3 bucket versioning (ref: rgw_bucket_dir_entry instances;
    S3 Enabled/Suspended semantics, delete markers, null versions)."""

    def _vb(self):
        c, gw = mk()
        gw.create_bucket("vb")
        gw.set_bucket_versioning("vb", True)
        return c, gw

    def test_status_transitions(self):
        c, gw = mk()
        gw.create_bucket("b")
        assert gw.get_bucket_versioning("b") == "Off"
        gw.set_bucket_versioning("b", True)
        assert gw.get_bucket_versioning("b") == "Enabled"
        gw.set_bucket_versioning("b", False)
        assert gw.get_bucket_versioning("b") == "Suspended"

    def test_puts_append_versions_and_get_by_vid(self):
        c, gw = self._vb()
        gw.put_object("vb", "k", b"version one")
        gw.put_object("vb", "k", b"version two")
        gw.put_object("vb", "k", b"version three")
        assert gw.get_object("vb", "k") == b"version three"
        vs = gw.list_object_versions("vb")["versions"]
        assert [v["is_latest"] for v in vs] == [True, False, False]
        vids = [v["vid"] for v in vs]          # newest first
        assert gw.get_object("vb", "k", version_id=vids[2]) \
            == b"version one"
        assert gw.get_object("vb", "k", version_id=vids[1]) \
            == b"version two"
        assert gw.head_object("vb", "k",
                              version_id=vids[2])["size"] == 11

    def test_unversioned_delete_writes_marker_and_undelete(self):
        c, gw = self._vb()
        gw.put_object("vb", "k", b"precious")
        res = gw.delete_object("vb", "k")
        assert res["delete_marker"] is True
        with pytest.raises(NoSuchKey):
            gw.get_object("vb", "k")           # current view gone
        vs = gw.list_object_versions("vb")["versions"]
        assert vs[0]["delete_marker"] and vs[0]["is_latest"]
        # the old payload is still there by vid
        assert gw.get_object("vb", "k",
                             version_id=vs[1]["vid"]) == b"precious"
        # removing the MARKER by vid undeletes (S3 undelete recipe)
        gw.delete_object("vb", "k", version_id=res["version_id"])
        assert gw.get_object("vb", "k") == b"precious"

    def test_delete_specific_version_permanent(self):
        c, gw = self._vb()
        gw.put_object("vb", "k", b"one")
        gw.put_object("vb", "k", b"two")
        vs = gw.list_object_versions("vb")["versions"]
        old_vid = vs[1]["vid"]
        gw.delete_object("vb", "k", version_id=old_vid)
        with pytest.raises(NoSuchKey):
            gw.get_object("vb", "k", version_id=old_vid)
        assert gw.get_object("vb", "k") == b"two"   # latest untouched
        # deleting the LAST version removes the key entirely
        cur = gw.list_object_versions("vb")["versions"]
        gw.delete_object("vb", "k", version_id=cur[0]["vid"])
        with pytest.raises(NoSuchKey):
            gw.get_object("vb", "k")
        assert gw.list_object_versions("vb")["versions"] == []

    def test_suspended_null_version_replaces(self):
        c, gw = self._vb()
        gw.put_object("vb", "k", b"enabled era")
        gw.set_bucket_versioning("vb", False)    # suspend
        gw.put_object("vb", "k", b"null one")
        gw.put_object("vb", "k", b"null two")    # replaces null one
        assert gw.get_object("vb", "k") == b"null two"
        vs = gw.list_object_versions("vb")["versions"]
        assert [v["vid"] == "null" for v in vs] == [True, False]
        assert len(vs) == 2                      # enabled-era + null
        assert gw.get_object("vb", "k",
                             version_id=vs[1]["vid"]) == b"enabled era"

    def test_legacy_object_materializes_as_null(self):
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"pre-versioning")
        gw.set_bucket_versioning("b", True)
        gw.put_object("b", "k", b"post-versioning")
        vs = gw.list_object_versions("b")["versions"]
        assert [v["vid"] for v in vs][-1] == "null"   # oldest = legacy
        assert gw.get_object("b", "k", version_id="null") \
            == b"pre-versioning"
        assert gw.get_object("b", "k") == b"post-versioning"

    def test_multipart_versions(self):
        c, gw = self._vb()
        gw.put_object("vb", "k", b"plain old")
        uid = gw.initiate_multipart("vb", "k")
        gw.upload_part("vb", "k", uid, 1, b"P" * 70000)
        gw.upload_part("vb", "k", uid, 2, b"Q" * 50000)
        gw.complete_multipart("vb", "k", uid)
        assert gw.get_object("vb", "k") == b"P" * 70000 + b"Q" * 50000
        vs = gw.list_object_versions("vb")["versions"]
        assert gw.get_object("vb", "k",
                             version_id=vs[1]["vid"]) == b"plain old"
        # deleting the multipart VERSION wipes its parts, not history
        gw.delete_object("vb", "k", version_id=vs[0]["vid"])
        assert gw.get_object("vb", "k") == b"plain old"

    def test_delete_bucket_blocked_by_noncurrent(self):
        c, gw = self._vb()
        gw.put_object("vb", "k", b"v")
        gw.delete_object("vb", "k")              # marker: list empty
        assert gw.list_objects("vb")["entries"] == []
        with pytest.raises(GatewayError, match="BucketNotEmpty"):
            gw.delete_bucket("vb")
        vs = gw.list_object_versions("vb")["versions"]
        for v in vs:
            gw.delete_object("vb", "k", version_id=v["vid"])
        gw.delete_bucket("vb")                   # now truly empty

    def test_versioning_over_signed_surface(self):
        import time as _t
        c, gw = mk()
        from ceph_tpu.rgw import AuthedGateway, S3Client, UserStore
        users = UserStore()
        access, secret = users.create_user("alice")
        agw = AuthedGateway(gw, users)
        s3 = S3Client(agw, access, secret)
        s3.create_bucket("b")
        s3.put_bucket_versioning("b", True)
        assert s3.get_bucket_versioning("b") == "Enabled"
        s3.put_object("b", "k", b"one")
        s3.put_object("b", "k", b"two")
        vs = s3.list_object_versions("b")["versions"]
        assert s3.get_object("b", "k",
                             version_id=vs[1]["vid"]) == b"one"
        res = s3.delete_object("b", "k")
        assert res["delete_marker"] is True
        # version_id is inside the signed canonical request: a
        # tampered vid must not verify
        from ceph_tpu.rgw.auth import SignatureDoesNotMatch, amz_date, sign
        date = amz_date(_t.time())
        sig = sign(secret, date, "get_object", "b", "k", "n1",
                   {"offset": 0, "length": None,
                    "version_id": vs[1]["vid"]}, b"")
        with pytest.raises(SignatureDoesNotMatch):
            agw.call(access, date, sig, "get_object", bucket="b",
                     key="k", nonce="n1", payload=b"", offset=0,
                     length=None, version_id=vs[0]["vid"])


class TestDelimiterListing:
    """ListObjectsV2 delimiter rollup (ref: RGWListBucket::execute
    common-prefix aggregation)."""

    def _seed(self):
        c, gw = mk()
        gw.create_bucket("b")
        for k in ("docs/a.txt", "docs/b.txt", "docs/sub/c.txt",
                  "logs/1.log", "logs/2.log", "top.txt"):
            gw.put_object("b", k, b"x")
        return gw

    def test_folder_view(self):
        gw = self._seed()
        out = gw.list_objects("b", delimiter="/")
        assert [e["key"] for e in out["entries"]] == ["top.txt"]
        assert out["common_prefixes"] == ["docs/", "logs/"]
        assert not out["truncated"]

    def test_prefix_plus_delimiter_descends_one_level(self):
        gw = self._seed()
        out = gw.list_objects("b", prefix="docs/", delimiter="/")
        assert [e["key"] for e in out["entries"]] == \
            ["docs/a.txt", "docs/b.txt"]
        assert out["common_prefixes"] == ["docs/sub/"]

    def test_delimiter_pagination(self):
        gw = self._seed()
        page1 = gw.list_objects("b", delimiter="/", limit=1)
        assert page1["truncated"]
        seen = list(page1["common_prefixes"]) \
            + [e["key"] for e in page1["entries"]]
        marker = page1["next_marker"]
        while marker:
            page = gw.list_objects("b", delimiter="/", limit=1,
                                   marker=marker)
            seen += list(page["common_prefixes"]) \
                + [e["key"] for e in page["entries"]]
            marker = page["next_marker"]
        assert sorted(seen) == ["docs/", "logs/", "top.txt"]

    def test_no_delimiter_unchanged(self):
        gw = self._seed()
        out = gw.list_objects("b", prefix="docs/")
        assert len(out["entries"]) == 3
        assert "common_prefixes" not in out

    def test_plain_key_marker_still_surfaces_prefix(self):
        """S3 semantics: a marker that is a plain key INSIDE a prefix
        does not hide the prefix — the remaining keys under it still
        roll up (only a rolled-prefix marker skips the whole run)."""
        gw = self._seed()
        out = gw.list_objects("b", marker="docs/a.txt", delimiter="/")
        assert "docs/" in out["common_prefixes"]
        assert "logs/" in out["common_prefixes"]

    def test_folder_marker_object_does_not_hide_subtree(self):
        """A zero-byte 'dir/' marker object (S3-console style) listed
        as an entry must not make the next page skip the subtree —
        the marker==prefix case is a key marker, not a rollup."""
        c, gw = mk()
        gw.create_bucket("b")
        for k in ("a/", "a/b", "a/c"):
            gw.put_object("b", k, b"")
        p1 = gw.list_objects("b", prefix="a/", delimiter="/", limit=1)
        assert [e["key"] for e in p1["entries"]] == ["a/"]
        assert p1["truncated"]
        p2 = gw.list_objects("b", prefix="a/", delimiter="/",
                             marker=p1["next_marker"])
        assert [e["key"] for e in p2["entries"]] == ["a/b", "a/c"]
        assert not p2["truncated"]

    def test_delimiter_over_signed_surface(self):
        """The SigV4 client exposes delimiter too — the folder view
        must be reachable WITHOUT bypassing auth."""
        from ceph_tpu.rgw import AuthedGateway, S3Client, UserStore
        gw = self._seed()
        users = UserStore()
        access, secret = users.create_user("lister")
        agw = AuthedGateway(gw, users)
        agw.adopt_bucket("b", "lister")   # raw-seeded bucket: link it
        cl = S3Client(agw, access, secret)
        out = cl.list_objects("b", delimiter="/")
        assert out["common_prefixes"] == ["docs/", "logs/"]


class TestCopyObject:
    """Server-side copy (ref: rgw_op.cc RGWCopyObj; S3
    x-amz-copy-source incl. versioned sources)."""

    def test_copy_across_buckets(self):
        c, gw = mk()
        gw.create_bucket("src")
        gw.create_bucket("dst")
        gw.put_object("src", "a", b"copy me" * 100)
        etag = gw.copy_object("src", "a", "dst", "b")
        assert gw.get_object("dst", "b") == b"copy me" * 100
        assert gw.head_object("dst", "b")["etag"] == etag
        # source untouched; payloads independent
        gw.delete_object("src", "a")
        assert gw.get_object("dst", "b") == b"copy me" * 100

    def test_copy_specific_version(self):
        c, gw = mk()
        gw.create_bucket("b")
        gw.set_bucket_versioning("b", True)
        gw.put_object("b", "doc", b"v1")
        v1 = [v["vid"] for v in
              gw.list_object_versions("b")["versions"]][0]
        gw.put_object("b", "doc", b"v2")
        gw.copy_object("b", "doc", "b", "restored",
                       src_version_id=v1)
        assert gw.get_object("b", "restored") == b"v1"

    def test_self_copy_rejected(self):
        c, gw = mk()
        gw.create_bucket("b")
        gw.put_object("b", "k", b"x")
        with pytest.raises(GatewayError, match="itself"):
            gw.copy_object("b", "k", "b", "k")

    def test_copy_into_versioned_dst_appends(self):
        c, gw = mk()
        gw.create_bucket("src")
        gw.create_bucket("dst")
        gw.set_bucket_versioning("dst", True)
        gw.put_object("dst", "k", b"old")
        gw.put_object("src", "k", b"new")
        gw.copy_object("src", "k", "dst", "k")
        assert gw.get_object("dst", "k") == b"new"
        assert len(gw.list_object_versions("dst")["versions"]) == 2

    def test_signed_copy_and_cross_user_denied(self):
        from ceph_tpu.rgw import AuthedGateway, S3Client, UserStore
        from ceph_tpu.rgw.auth import AccessDenied
        c, gw = mk()
        users = UserStore()
        a_ak, a_sk = users.create_user("alice")
        b_ak, b_sk = users.create_user("bob")
        agw = AuthedGateway(gw, users)
        alice = S3Client(agw, a_ak, a_sk)
        bob = S3Client(agw, b_ak, b_sk)
        alice.create_bucket("alices")
        bob.create_bucket("bobs")
        alice.put_object("alices", "secret", b"classified")
        with pytest.raises(AccessDenied, match="source bucket"):
            bob.copy_object("alices", "secret", "bobs", "stolen")
        alice.create_bucket("alices2")
        alice.copy_object("alices", "secret", "alices2", "copy")
        assert alice.get_object("alices2", "copy") == b"classified"

    def test_unknown_owner_source_denied(self):
        """A bucket created on the raw Gateway (no recorded owner)
        must not be world-readable through authed copy_object (r4
        advisor finding)."""
        from ceph_tpu.rgw import AuthedGateway, S3Client, UserStore
        from ceph_tpu.rgw.auth import AccessDenied
        c, gw = mk()
        gw.create_bucket("orphan")
        gw.put_object("orphan", "k", b"no owner on file")
        users = UserStore()
        a_ak, a_sk = users.create_user("alice")
        agw = AuthedGateway(gw, users)
        alice = S3Client(agw, a_ak, a_sk)
        alice.create_bucket("mine")
        with pytest.raises(AccessDenied, match="no recorded owner"):
            alice.copy_object("orphan", "k", "mine", "grab")
        # every other op on an orphan bucket is denied too — unknown
        # ownership must not read as world-access
        for attempt in (
                lambda: alice.get_object("orphan", "k"),
                lambda: alice.put_object("orphan", "k2", b"sneak"),
                lambda: alice.delete_object("orphan", "k"),
                lambda: alice.list_objects("orphan")):
            with pytest.raises(AccessDenied, match="no recorded owner"):
                attempt()
        # and the orphan's name never shows in anyone's listing
        assert alice.list_buckets() == ["mine"]
