"""Sharded-OSD dispatch invariants (r13): per-PG ordering under
`osd_op_num_shards > 1`, batch split-join across shards, per-shard
occupancy observability, and host-encode bit-parity.

The ordering contract: ops hash by PG id to a shard, each shard
drains FIFO (same mClock class, seq-ordered heap) on one worker —
interleaved writes to ONE PG must execute in arrival order even while
cross-PG ops overlap on other shards. The test submits pipelined raw
MOSDOp frames (no client-side waits, the test_op_window idiom) so the
queue really holds many same-PG ops at once."""

import numpy as np
import pytest

from ceph_tpu.msgr.messenger import Messenger
from ceph_tpu.osd.standalone import (MOSDOp, MOSDOpReply,
                                     StandaloneCluster, _Rpc)
from ceph_tpu.utils.encoding import Decoder, Encoder


@pytest.fixture(scope="module")
def sharded_cluster():
    c = StandaloneCluster(
        n_osds=4, pg_num=4, op_shards=2, msgr_workers=2,
        profile="plugin=tpu_rs k=2 m=1 impl=bitlinear")
    c.wait_for_clean(timeout=30)
    yield c
    c.shutdown()


def _raw_client(c):
    """A bare messenger + rpc speaking the client op protocol (no
    cephx on this cluster, so the auth gate is off)."""
    m = Messenger("client.raw")
    rpc = _Rpc(m, MOSDOpReply.type_id)
    for d in c.osds.values():
        m.add_peer(d.name, d.msgr.addr)
    return m, rpc


def _write_body(ps: int, name: str, data: bytes) -> bytes:
    e = Encoder()
    e.u32(ps)
    e.u64(0)                     # snapc
    e.mapping({name: data}, Encoder.string, Encoder.blob)
    return e.bytes()


def _read_body(ps: int, name: str) -> bytes:
    e = Encoder()
    e.u32(ps)
    e.string(name)
    return e.bytes()


def _primary(c, ps: int) -> str:
    m = c.mons[0].osdmap
    return f"osd.{m.pg_to_up_acting_osds(1, ps)[2][0]}"


class TestPerPGOrdering:
    def test_interleaved_same_pg_writes_stay_ordered(
            self, sharded_cluster):
        """30 pipelined writes to ONE object (same PG, no waits
        between submits) interleaved with cross-PG traffic: the final
        bytes must be the LAST submitted value — a queue-level
        reorder would leave an earlier value on top."""
        c = sharded_cluster
        m, rpc = _raw_client(c)
        try:
            handles = []
            for i in range(30):
                tgt0 = _primary(c, 0)
                handles.append(rpc.submit(
                    tgt0, lambda rid, i=i: MOSDOp(
                        rid, True, "write",
                        _write_body(0, "ordered", bytes([i]) * 512))))
                # overlapping cross-PG op: lands in the OTHER shard
                # (pg 1 % 2 != pg 0 % 2) and must not perturb pg 0's
                # order
                tgt1 = _primary(c, 1)
                handles.append(rpc.submit(
                    tgt1, lambda rid, i=i: MOSDOp(
                        rid, True, "write",
                        _write_body(1, f"x{i}", b"z" * 256))))
            for h in handles:
                rep = h.wait(20.0)
                assert rep.ok, rep.err
            rep = rpc.call(_primary(c, 0),
                           lambda rid: MOSDOp(rid, True, "read",
                                              _read_body(0,
                                                         "ordered")),
                           timeout=20.0)
            assert rep.ok, rep.err
            assert bytes(rep.blob) == bytes([29]) * 512
        finally:
            m.shutdown()

    def test_cross_pg_ops_really_spread_over_shards(
            self, sharded_cluster):
        """The occupancy evidence: after traffic to every PG, at
        least one daemon's dump_op_shards shows grants on BOTH
        shards (pg % 2 covers both residues)."""
        c = sharded_cluster
        cl = c.client()
        objs = {f"spread-{i}": bytes([i]) * 1024 for i in range(32)}
        cl.write(objs)
        for n, v in objs.items():
            assert bytes(cl.read(n)) == v
        spread = False
        for osd in c.osd_ids():
            dump = cl.daemon(osd, "dump_op_shards")
            assert set(dump) == {"shard_0", "shard_1"}
            served = [sum(row["served"] for row in shard.values())
                      for shard in dump.values()]
            if all(s > 0 for s in served):
                spread = True
        assert spread, "no daemon served ops on both shards"

    def test_batch_frame_splits_and_rejoins_in_slot_order(
            self, sharded_cluster):
        """A `batch` frame whose sub-ops span BOTH shards: the reply
        must carry every slot, in the original order, each ok — the
        split-join path (_BatchJoin) at work. PGs are chosen so one
        primary owns PGs in both shard residues when possible;
        otherwise the single-group fast path serves it (both are
        correct, the wire contract is identical)."""
        c = sharded_cluster
        m, rpc = _raw_client(c)
        try:
            # find a primary owning >= 2 PGs in different shards
            by_primary: dict[str, list[int]] = {}
            for ps in range(4):
                by_primary.setdefault(_primary(c, ps), []).append(ps)
            tgt, pgs = max(by_primary.items(),
                           key=lambda kv: len({p % 2
                                               for p in kv[1]}))
            e = Encoder()
            subs = [(ps, f"batch-{ps}-{j}") for ps in pgs
                    for j in range(2)]
            e.u32(len(subs))
            for slot, (ps, name) in enumerate(subs):
                e.string("write")
                e.blob(_write_body(ps, name, bytes([slot]) * 128))
            rep = rpc.call(tgt, lambda rid: MOSDOp(
                rid, True, "batch", e.bytes()), timeout=20.0)
            assert rep.ok, rep.err
            d = Decoder(rep.blob)
            nslots = d.u32()
            assert nslots == len(subs)
            for slot in range(nslots):
                ok, blob, err = d.boolean(), d.blob(), d.string()
                assert ok, (slot, err)
            # and the writes really landed, bit-exact
            for slot, (ps, name) in enumerate(subs):
                rep = rpc.call(tgt, lambda rid, ps=ps, name=name:
                               MOSDOp(rid, True, "read",
                                      _read_body(ps, name)),
                               timeout=20.0)
                assert rep.ok and bytes(rep.blob) == \
                    bytes([slot]) * 128, (slot, name)
        finally:
            m.shutdown()


class TestHostEncodeParity:
    def test_host_encode_bit_identical_to_fused_device_launch(self):
        """The r13 write-path host-encode mode (native SSE RS +
        hardware crc32c on the CPU backend) must produce EXACTLY the
        fused device launch's shards and hinfo CRCs — same coding
        matrix, bit-for-bit."""
        from ceph_tpu.osd import ecbackend as EB
        from ceph_tpu.osd.ecbackend import ECBackend, ShardSet
        if not EB._host_crc_available():
            pytest.skip("native codec/hw-crc unavailable")
        profile = "plugin=tpu_rs k=4 m=2 impl=bitlinear"
        be = ECBackend(profile, "1.0", list(range(6)), ShardSet(),
                       chunk_size=256)
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, (5, 4, 1024), np.uint8)
        host_shards, host_crcs = be._encode_shards_with_crcs(data,
                                                             1024)
        # force the device path by disabling the host gate
        orig = EB._host_crc_available
        EB._host_crc_available = lambda: False
        try:
            dev_shards, dev_crcs = be._encode_shards_with_crcs(data,
                                                               1024)
        finally:
            EB._host_crc_available = orig
        assert np.array_equal(host_shards, dev_shards)
        assert np.array_equal(np.asarray(host_crcs, np.uint32),
                              np.asarray(dev_crcs, np.uint32))
