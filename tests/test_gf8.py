"""GF(2^8) arithmetic core tests.

Field-axiom and known-value tests for the table layer (the rebuild's
equivalent of gf-complete's gf_unit; ref: jerasure/gf-complete test
strategy in SURVEY.md §4 tier 1).
"""

import numpy as np
import pytest

from ceph_tpu.gf import tables as T
from ceph_tpu.gf import numpy_ref as R


def test_known_values_poly_0x11d():
    # alpha = 2; 2*128 = 0x100 -> reduced by 0x11D -> 0x1D
    assert T.gf_mul_scalar(2, 128) == 0x1D
    assert T.gf_mul_scalar(0, 77) == 0
    assert T.gf_mul_scalar(1, 77) == 77
    # exp table starts 1, 2, 4, ..., 128, 0x1D
    assert list(T.GF_EXP[:9]) == [1, 2, 4, 8, 16, 32, 64, 128, 0x1D]


def test_exp_log_roundtrip():
    for a in range(1, 256):
        assert T.GF_EXP[T.GF_LOG[a]] == a


def test_mul_table_matches_scalar():
    mt = T.mul_table()
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b = int(rng.integers(256)), int(rng.integers(256))
        assert mt[a, b] == T.gf_mul_scalar(a, b)


def test_field_axioms_sampled():
    mt = T.mul_table()
    rng = np.random.default_rng(1)
    abc = rng.integers(0, 256, size=(100, 3))
    for a, b, c in abc:
        # commutativity, associativity, distributivity over XOR
        assert mt[a, b] == mt[b, a]
        assert mt[a, mt[b, c]] == mt[mt[a, b], c]
        assert mt[a, b ^ c] == mt[a, b] ^ mt[a, c]


def test_inverse():
    inv = T.inv_table()
    mt = T.mul_table()
    for a in range(1, 256):
        assert mt[a, inv[a]] == 1
    with pytest.raises(ZeroDivisionError):
        T.gf_inv_scalar(0)


def test_nibble_tables_decompose_mul():
    lo, hi = T.nibble_tables()
    mt = T.mul_table()
    rng = np.random.default_rng(2)
    for _ in range(200):
        c, x = int(rng.integers(256)), int(rng.integers(256))
        assert mt[c, x] == lo[c, x & 0xF] ^ hi[c, x >> 4]


def test_bit_powers_linearity():
    P = T.bit_powers()
    mt = T.mul_table()
    rng = np.random.default_rng(3)
    for _ in range(200):
        c, x = int(rng.integers(256)), int(rng.integers(256))
        acc = 0
        for b in range(8):
            if (x >> b) & 1:
                acc ^= int(P[c, b])
        assert acc == mt[c, x]


def test_bitmatrix_matches_mul():
    rng = np.random.default_rng(4)
    for _ in range(50):
        c, x = int(rng.integers(256)), int(rng.integers(256))
        M = T.gf_bitmatrix(c)
        xbits = np.array([(x >> b) & 1 for b in range(8)], dtype=np.uint8)
        ybits = (M @ xbits) % 2
        y = sum(int(v) << b for b, v in enumerate(ybits))
        assert y == T.gf_mul_scalar(c, x)


def test_gf_matmul_identity_and_inverse():
    rng = np.random.default_rng(5)
    for n in (2, 4, 8):
        # random invertible matrix via random tries
        while True:
            A = rng.integers(0, 256, size=(n, n)).astype(np.uint8)
            try:
                Ainv = R.gf_inv_matrix(A)
                break
            except ValueError:
                continue
        assert (R.gf_matmul(A, Ainv) == np.eye(n, dtype=np.uint8)).all()
        assert (R.gf_matmul(Ainv, A) == np.eye(n, dtype=np.uint8)).all()


def test_singular_matrix_raises():
    A = np.array([[1, 2], [1, 2]], dtype=np.uint8)
    with pytest.raises(ValueError):
        R.gf_inv_matrix(A)
