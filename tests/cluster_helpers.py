"""Shared SimCluster test helpers (used by test_cluster / test_pglog /
test_backfill / test_objecter)."""

import numpy as np

from ceph_tpu.osd.cluster import SimCluster


def make_cluster(**kw):
    kw.setdefault("n_osds", 12)
    kw.setdefault("pg_num", 8)
    kw.setdefault("heartbeat_grace", 20.0)
    kw.setdefault("down_out_interval", 60.0)
    return SimCluster(**kw)


def corpus(n=24, size=700, seed=0, prefix="obj"):
    rng = np.random.default_rng(seed)
    return {f"{prefix}-{i}": rng.integers(0, 256, size=size, dtype=np.uint8)
            for i in range(n)}
