"""Checksum subsystem tests.

Mirrors the reference's checksum test tiers: known-vector pinning
(ref: src/test/common/test_crc32c.cc style), oracle-vs-kernel
bit-exactness sweeps, and Checksummer calculate/verify semantics
(ref: src/test/objectstore/ tests of BlueStore _verify_csum behavior).
"""

import numpy as np
import pytest

from ceph_tpu.csum import (CSUM_ALGORITHMS, Checksummer, ceph_crc32c, crc32c,
                           xxh32, xxh64)
from ceph_tpu.csum.kernels import crc32c_blocks, xxh32_blocks, xxh64_blocks
from ceph_tpu.csum.reference import apply_shift


class TestKnownVectors:
    """Published vectors — pin the algorithms, not our own output."""

    def test_crc32c_rfc3720(self):
        # RFC 3720 B.4 test vectors
        assert crc32c(bytes(32)) == 0x8A9136AA
        assert crc32c(b"\xff" * 32) == 0x62A8AB43
        assert crc32c(bytes(range(32))) == 0x46DD794E
        assert crc32c(bytes(range(31, -1, -1))) == 0x113FDB5C

    def test_crc32c_check_string(self):
        assert crc32c(b"123456789") == 0xE3069283
        assert crc32c(b"") == 0
        assert crc32c(b"a") == 0xC1D04330

    def test_xxh32_vectors(self):
        assert xxh32(b"") == 0x02CC5D05
        assert xxh32(b"a") == 0x550D7456
        assert xxh32(b"abc") == 0x32D153FF

    def test_xxh64_vectors(self):
        assert xxh64(b"") == 0xEF46DB3751D8E999
        assert xxh64(b"a") == 0xD24EC4F1A98C6E5B
        assert xxh64(b"abc") == 0x44BC2CF5AD770999

    def test_xxh_seeded(self):
        # seed changes the hash; chaining sanity
        assert xxh32(b"abc", 1) != xxh32(b"abc", 0)
        assert xxh64(b"abc", 1) != xxh64(b"abc", 0)


class TestCephConvention:
    def test_chaining(self):
        a, b = b"hello ", b"world"
        assert ceph_crc32c(ceph_crc32c(5, a), b) == ceph_crc32c(5, a + b)

    def test_shift_is_zero_bytes(self):
        r = ceph_crc32c(0xDEADBEEF, b"xyz")
        for n in (0, 1, 7, 8, 9, 100, 4096):
            assert apply_shift(r, n) == ceph_crc32c(r, bytes(n))


@pytest.mark.parametrize("length", [0, 1, 5, 8, 16, 63, 64, 100, 4096, 4099])
def test_crc32c_kernel_matches_oracle(length):
    rng = np.random.default_rng(length)
    data = rng.integers(0, 256, size=(5, length), dtype=np.uint8)
    got = np.asarray(crc32c_blocks(data))
    want = np.array([crc32c(row.tobytes()) for row in data], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)
    # ceph raw-register convention
    got = np.asarray(crc32c_blocks(data, init=0xFFFFFFFF, xorout=0))
    want = np.array([ceph_crc32c(0xFFFFFFFF, row.tobytes()) for row in data],
                    dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("length", [0, 1, 3, 4, 15, 16, 17, 31, 32, 33, 100,
                                    4096])
def test_xxh_kernels_match_oracle(length):
    rng = np.random.default_rng(1000 + length)
    data = rng.integers(0, 256, size=(4, length), dtype=np.uint8)
    g32 = np.asarray(xxh32_blocks(data, seed=42))
    w32 = np.array([xxh32(row.tobytes(), 42) for row in data],
                   dtype=np.uint32)
    np.testing.assert_array_equal(g32, w32)
    g64 = np.asarray(xxh64_blocks(data, seed=42)).astype(np.uint64)
    g64v = (g64[:, 0] << np.uint64(32)) | g64[:, 1]
    w64 = np.array([xxh64(row.tobytes(), 42) for row in data],
                   dtype=np.uint64)
    np.testing.assert_array_equal(g64v, w64)


class TestChecksummer:
    @pytest.mark.parametrize("algo", CSUM_ALGORITHMS)
    def test_device_matches_host(self, algo):
        cs = Checksummer(algo, block_size=256)
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, size=8 * 256, dtype=np.uint8)
        np.testing.assert_array_equal(cs.calculate(data),
                                      cs.calculate(data, device=False))

    def test_verify_clean(self):
        cs = Checksummer("crc32c", block_size=128)
        data = np.arange(4 * 128, dtype=np.uint8) % 251
        assert cs.verify(data, cs.calculate(data)) == -1

    def test_verify_reports_first_bad_offset(self):
        cs = Checksummer("crc32c", block_size=128)
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, size=6 * 128, dtype=np.uint8)
        sums = cs.calculate(data)
        corrupt = data.copy()
        corrupt[2 * 128 + 5] ^= 0x40  # flip a bit in block 2
        corrupt[5 * 128] ^= 0x01      # and block 5
        assert cs.verify(corrupt, sums) == 2 * 128

    def test_truncated_variants(self):
        data = np.arange(512, dtype=np.uint8)
        full = Checksummer("crc32c", 256).calculate(data)
        np.testing.assert_array_equal(
            Checksummer("crc32c_16", 256).calculate(data), full & 0xFFFF)
        np.testing.assert_array_equal(
            Checksummer("crc32c_8", 256).calculate(data), full & 0xFF)

    def test_bad_sizes_rejected(self):
        cs = Checksummer("crc32c", block_size=128)
        with pytest.raises(ValueError):
            cs.calculate(np.zeros(100, np.uint8))
        with pytest.raises(ValueError):
            Checksummer("nope", 128)

    def test_value_sizes(self):
        assert Checksummer("crc32c", 4096).csum_value_size == 4
        assert Checksummer("crc32c_16", 4096).csum_value_size == 2
        assert Checksummer("crc32c_8", 4096).csum_value_size == 1
        assert Checksummer("xxhash64", 4096).csum_value_size == 8


class TestCrc32cExtend:
    """crc32c_extend buckets block length to powers of two and undoes the
    zero-padding shift — must match serial ceph_crc32c for ANY length."""

    def test_arbitrary_lengths_match_serial(self):
        import numpy as np
        from ceph_tpu.csum.kernels import crc32c_extend
        from ceph_tpu.csum.reference import ceph_crc32c
        rng = np.random.default_rng(11)
        for L in [1, 2, 3, 7, 13, 63, 64, 65, 100, 257, 1000]:
            blocks = rng.integers(0, 256, size=(3, L), dtype=np.uint8)
            regs = rng.integers(0, 1 << 32, size=3, dtype=np.uint32)
            got = np.asarray(crc32c_extend(regs, blocks))
            want = [ceph_crc32c(int(r), b) for r, b in zip(regs, blocks)]
            assert got.tolist() == want, L

    def test_chaining(self):
        import numpy as np
        from ceph_tpu.csum.kernels import crc32c_extend
        from ceph_tpu.csum.reference import ceph_crc32c
        rng = np.random.default_rng(12)
        a = rng.integers(0, 256, size=(2, 37), dtype=np.uint8)
        b = rng.integers(0, 256, size=(2, 91), dtype=np.uint8)
        regs = np.full(2, 0xFFFFFFFF, np.uint32)
        step = crc32c_extend(crc32c_extend(regs, a), b)
        whole = [ceph_crc32c(0xFFFFFFFF, np.concatenate([a[i], b[i]]))
                 for i in range(2)]
        assert np.asarray(step).tolist() == whole
