"""ReplicatedBackend + replicated-pool SimCluster tests — the
PGBackend-interface parity suite (ref: ReplicatedBackend is exercised
by the same store_test/osd suites as ECBackend; the backend split is
src/osd/PGBackend.h)."""

import numpy as np
import pytest

from ceph_tpu.osd.ecbackend import ShardSet
from ceph_tpu.osd.pgbackend import (HINFO_KEY, ReplicatedBackend,
                                    shard_cid)

from cluster_helpers import corpus, make_cluster


def make_be(size=3, min_size=2, pg="1.0"):
    cluster = ShardSet()
    return ReplicatedBackend(size, pg, list(range(size)), cluster,
                             min_size=min_size), cluster


class TestReplicatedBackend:
    def test_write_read_roundtrip(self):
        be, _ = make_be()
        objs = corpus(8, 300, seed=1)
        be.write_objects(objs)
        for name, data in objs.items():
            assert np.array_equal(be.read_object(name), data)

    def test_every_replica_holds_full_copy(self):
        be, cluster = make_be()
        be.write_objects({"o": b"payload"})
        for s in range(be.size):
            st = cluster.osd(be.acting[s])
            assert st.read(shard_cid(be.pg, s), "o").tobytes() == b"payload"
            assert st.getattr(shard_cid(be.pg, s), "o", HINFO_KEY)

    def test_write_ranges_overlay_and_extend(self):
        be, _ = make_be()
        be.write_objects({"o": bytes(range(100))})
        be.write_at("o", 10, b"\xff" * 5)
        be.write_at("o", 95, b"\xaa" * 20)  # extends to 115
        want = bytearray(range(100))
        want[10:15] = b"\xff" * 5
        want += bytes(15)
        want[95:115] = b"\xaa" * 20
        assert be.read_object("o").tobytes() == bytes(want)
        assert be.object_sizes["o"] == 115

    def test_degraded_write_then_read(self):
        be, _ = make_be()
        objs = corpus(4, 200, seed=2)
        be.write_objects(objs, dead_osds={be.acting[0]})
        # reads must come from a caught-up replica, not the stale slot 0
        for name, data in objs.items():
            got = be.read_object(name, dead_osds={be.acting[0]})
            assert np.array_equal(got, data)
        # even with slot 0's OSD "alive" again, it is stale until replay
        for name, data in objs.items():
            assert np.array_equal(be.read_object(name), data)

    def test_min_size_gate(self):
        be, _ = make_be(size=3, min_size=2)
        with pytest.raises(ValueError, match="min_size"):
            be.write_objects({"o": b"x"},
                             dead_osds={be.acting[0], be.acting[1]})

    def test_recover_push(self):
        be, cluster = make_be()
        objs = corpus(10, 400, seed=3)
        be.write_objects(objs)
        dead = be.acting[1]
        cluster.stores.pop(dead)
        counters = be.recover_shards([1], replacement_osds={1: 100})
        assert counters["objects"] == len(objs)
        assert be.acting[1] == 100
        st = cluster.osd(100)
        for name, data in objs.items():
            assert np.array_equal(st.read(shard_cid(be.pg, 1), name), data)

    def test_recover_failover_on_corrupt_source(self):
        be, cluster = make_be()
        objs = corpus(4, 256, seed=4)
        be.write_objects(objs)
        # corrupt the primary copy of one object (source slot 0 is
        # preferred); recovery must fail its digest and pull from slot 2
        st0 = cluster.osd(be.acting[0])
        from ceph_tpu.osd.memstore import Transaction
        st0.queue_transaction(Transaction().write(
            shard_cid(be.pg, 0), "obj-2", 5, b"\x00\x01\x02"))
        cluster.stores.pop(be.acting[1])
        counters = be.recover_shards([1], replacement_osds={1: 50})
        assert counters["hinfo_failures"] >= 1
        got = cluster.osd(50).read(shard_cid(be.pg, 1), "obj-2")
        assert np.array_equal(got, objs["obj-2"])

    def test_deep_scrub_detects_bit_rot(self):
        be, cluster = make_be()
        be.write_objects(corpus(6, 128, seed=5))
        rep = be.deep_scrub()
        assert rep["inconsistent"] == [] and rep["digest_mismatch"] == []
        st = cluster.osd(be.acting[2])
        obj = st.collections[shard_cid(be.pg, 2)]["obj-3"]
        obj.data[7] ^= 0x40
        rep = be.deep_scrub()
        assert ("obj-3", 2) in rep["inconsistent"]
        assert "obj-3" in rep["digest_mismatch"]

    def test_delta_replay_names_restriction(self):
        be, _ = make_be()
        be.write_objects({"a": b"one", "b": b"two"})
        dead = be.acting[2]
        be.write_objects({"c": b"three"}, dead_osds={dead})
        missed = be.pg_log.missing_since(be.shard_applied[2])
        assert missed == ["c"]
        counters = be.recover_shards([2], names=missed)
        assert counters["objects"] == 1
        assert be.shard_applied[2] == be.pg_log.head


class TestReplicatedCluster:
    def test_write_kill_out_recover_verify(self):
        c = make_cluster(profile="replicated size=3", pg_num=4,
                         n_osds=8)
        assert not c.is_erasure
        objs = corpus(16, 500, seed=6)
        c.write(objs)
        c.kill_osd(3)
        c.tick(30)   # grace expiry -> down
        c.tick(90)   # down_out_interval -> out -> remap + recover
        for _ in range(40):
            if not c.backfills:
                break
            c.tick(6)
        assert c.verify_all(objs) == len(objs)
        h = c.health()
        assert h["pgs_degraded"] == 0

    def test_revive_replays_delta(self):
        c = make_cluster(profile="replicated size=3", pg_num=4,
                         n_osds=8, down_out_interval=10_000)
        objs = corpus(8, 300, seed=7)
        c.write(objs)
        c.kill_osd(2)
        c.tick(30)
        more = corpus(8, 300, seed=8, prefix="late")
        c.write(more)
        c.revive_osd(2)
        all_objs = {**objs, **more}
        assert c.verify_all(all_objs) == len(all_objs)
