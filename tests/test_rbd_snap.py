"""RBD snapshots + clone layering over self-managed rados snaps
(refs: src/librbd/Operations.cc snap_*, src/librbd/io/CopyupRequest.cc,
src/cls/rbd children bookkeeping, librados selfmanaged_snap_* +
per-op SnapContext in src/osdc/Objecter.cc)."""

import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.client.rbd import RBD, Image, ImageBusy, ImageHasSnapshots
from ceph_tpu.osd.cluster import SimCluster


def make_rbd(**kw):
    kw.setdefault("n_osds", 8)
    kw.setdefault("pg_num", 4)
    c = SimCluster(**kw)
    io = Rados(c).open_ioctx()
    return c, io, RBD(io, stripe_unit=256, stripe_count=2,
                      object_size=1024)


class TestSelfmanagedSnaps:
    """The rados-level machinery RBD snapshots ride on."""

    def test_mode_exclusivity(self):
        c, io, _ = make_rbd()
        io.selfmanaged_snap_create()
        with pytest.raises(ValueError, match="selfmanaged"):
            io.snap_create()
        c2 = SimCluster(n_osds=8, pg_num=4)
        io2 = Rados(c2).open_ioctx()
        io2.snap_create()
        with pytest.raises(ValueError, match="pool snapshots"):
            io2.selfmanaged_snap_create()

    def test_snapc_drives_cow_per_writer(self):
        """Only writes that NAME the snap in their SnapContext
        preserve clones — the per-image isolation property."""
        c, io, _ = make_rbd()
        io.write_full("a", b"A1")
        io.write_full("b", b"B1")
        sid = io.selfmanaged_snap_create()
        io.write_full("a", b"A2", snapc=sid)   # snap-aware writer
        io.write_full("b", b"B2")              # snap-oblivious writer
        assert io.read("a", snap=sid) == b"A1"
        assert io.read("a") == b"A2"
        assert "a" in c.snapsets and "b" not in c.snapsets

    def test_remove_with_snapc_preserves_clone(self):
        c, io, _ = make_rbd()
        io.write_full("a", b"keep me")
        sid = io.selfmanaged_snap_create()
        io.remove("a", snapc=sid)
        with pytest.raises(KeyError):
            io.read("a")
        assert io.read("a", snap=sid) == b"keep me"

    def test_snap_remove_trims_clones(self):
        c, io, _ = make_rbd()
        io.write_full("a", b"v1")
        sid = io.selfmanaged_snap_create()
        io.write_full("a", b"v2", snapc=sid)
        assert c.snapsets.get("a")
        trimmed = io.selfmanaged_snap_remove(sid)
        assert trimmed == 1 and "a" not in c.snapsets
        with pytest.raises(KeyError):
            io.read("a", snap=sid)

    def test_snap_changed_metadata_diff(self):
        c, io, _ = make_rbd()
        io.write_full("mut", b"x")
        io.write_full("still", b"y")
        sid = io.selfmanaged_snap_create()
        io.write_full("mut", b"x2", snapc=sid)
        io.write_full("born", b"z", snapc=sid)
        assert io.snap_changed("mut", sid) is True
        assert io.snap_changed("still", sid) is False
        assert io.snap_changed("born", sid) is True
        assert io.snap_changed("never-existed", sid) is False


class TestImageSnapshots:
    def test_snap_read_and_isolation_between_images(self):
        c, io, rbd = make_rbd()
        a = rbd.create("a", 4096)
        b = rbd.create("b", 4096)
        a.write(0, b"alpha-v1".ljust(512, b"."))
        b.write(0, b"beta-v1")
        a.snap_create("s1")
        a.write(0, b"alpha-v2".ljust(512, b"!"))
        b.write(0, b"beta-v2")       # b has no snaps: no COW for b
        assert a.read(0, 8) == b"alpha-v2"
        a.set_snap("s1")
        assert a.read(0, 8) == b"alpha-v1"
        assert a.size() == 4096
        with pytest.raises(ValueError, match="read-only"):
            a.write(0, b"nope")
        a.set_snap(None)
        assert b.read(0, 7) == b"beta-v2"
        # no clone objects exist for b's pieces
        assert not any(n.startswith("rbd_data.b.") for n in c.snapsets)

    def test_snap_records_size(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vol", 2048)
        img.write(0, b"D" * 2048)
        img.snap_create("small")
        img.resize(8192)
        img.write(4096, b"E" * 100)
        img.set_snap("small")
        assert img.size() == 2048
        assert img.read(0, 2048) == b"D" * 2048
        img.set_snap(None)
        assert img.size() == 8192

    def test_rollback(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vm", 2048)
        img.write(0, b"golden".ljust(2048, b"g"))
        img.snap_create("gold")
        img.write(0, b"corrupted".ljust(2048, b"#"))
        img.snap_rollback("gold")
        assert img.read(0, 6) == b"golden"
        assert img.read(0, 2048) == b"golden".ljust(2048, b"g")

    def test_rollback_preserves_newer_snap(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vm", 1024)
        img.write(0, b"one".ljust(64, b"1"))
        img.snap_create("s1")
        img.write(0, b"two".ljust(64, b"2"))
        img.snap_create("s2")
        img.snap_rollback("s1")
        assert img.read(0, 3) == b"one"
        img.set_snap("s2")
        assert img.read(0, 3) == b"two"

    def test_snap_remove_and_remove_guard(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vm", 1024)
        img.write(0, b"data")
        img.snap_create("s1")
        with pytest.raises(ImageHasSnapshots):
            rbd.remove("vm")
        img.snap_remove("s1")
        rbd.remove("vm")
        assert rbd.list() == []

    def test_duplicate_snap_name_refused(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vm", 1024)
        img.snap_create("s1")
        with pytest.raises(FileExistsError):
            img.snap_create("s1")


class TestCloneLayering:
    def _parent_with_snap(self, rbd, size=4096):
        p = rbd.create("parent", size)
        p.write(0, b"PARENT-DATA-".ljust(1024, b"P"))
        p.write(2048, b"TAIL".ljust(512, b"T"))
        p.snap_create("base")
        p.snap_protect("base")
        return p

    def test_clone_requires_protected_snap(self):
        c, io, rbd = make_rbd()
        p = rbd.create("parent", 1024)
        p.snap_create("s")
        with pytest.raises(ValueError, match="protected"):
            rbd.clone("parent", "s", "child")

    def test_clone_reads_fall_through_to_parent(self):
        c, io, rbd = make_rbd()
        p = self._parent_with_snap(rbd)
        child = rbd.clone("parent", "base", "child")
        assert child.size() == 4096
        assert child.read(0, 12) == b"PARENT-DATA-"
        assert child.read(2048, 4) == b"TAIL"
        assert child.read(3584, 512) == b"\x00" * 512  # sparse in both

    def test_parent_changes_after_snap_invisible_to_child(self):
        c, io, rbd = make_rbd()
        p = self._parent_with_snap(rbd)
        child = rbd.clone("parent", "base", "child")
        p.write(0, b"parent-moved-on".ljust(1024, b"x"))
        assert child.read(0, 12) == b"PARENT-DATA-"

    def test_copy_up_on_partial_write(self):
        """A write smaller than a stripe piece must materialize the
        piece from the parent first, preserving surrounding bytes."""
        c, io, rbd = make_rbd()
        self._parent_with_snap(rbd)
        child = rbd.clone("parent", "base", "child")
        child.write(4, b"####")
        got = child.read(0, 12)
        assert got == b"PARE####ATA-", got
        # parent untouched
        p = Image(rbd, "parent")
        assert p.read(0, 12) == b"PARENT-DATA-"

    def test_child_write_does_not_leak_to_parent_or_sibling(self):
        c, io, rbd = make_rbd()
        self._parent_with_snap(rbd)
        c1 = rbd.clone("parent", "base", "c1")
        c2 = rbd.clone("parent", "base", "c2")
        c1.write(0, b"CHILD-ONE".ljust(256, b"1"))
        assert c2.read(0, 12) == b"PARENT-DATA-"
        assert Image(rbd, "parent").read(0, 9) != b"CHILD-ONE"

    def test_grandchild_chain(self):
        c, io, rbd = make_rbd()
        self._parent_with_snap(rbd)
        child = rbd.clone("parent", "base", "child")
        child.write(256, b"CHILDLAYER".ljust(256, b"c"))
        child.snap_create("mid")
        child.snap_protect("mid")
        gc = rbd.clone("child", "mid", "grandchild")
        assert gc.read(0, 12) == b"PARENT-DATA-"   # via child via parent
        assert gc.read(256, 10) == b"CHILDLAYER"   # via child
        gc.write(512, b"GC".ljust(64, b"g"))
        assert gc.read(512, 2) == b"GC"
        assert Image(rbd, "child").read(512, 2) != b"GC"

    def test_unprotect_refused_while_children_exist(self):
        c, io, rbd = make_rbd()
        p = self._parent_with_snap(rbd)
        rbd.clone("parent", "base", "child")
        with pytest.raises(ImageBusy, match="child"):
            p.snap_unprotect("base")
        assert rbd.list_children("parent", "base") == ["child"]

    def test_protected_snap_remove_refused(self):
        c, io, rbd = make_rbd()
        p = self._parent_with_snap(rbd)
        with pytest.raises(ImageBusy, match="protected"):
            p.snap_remove("base")

    def test_flatten_severs_parent(self):
        c, io, rbd = make_rbd()
        p = self._parent_with_snap(rbd)
        child = rbd.clone("parent", "base", "child")
        child.write(4, b"####")
        before = child.read(0, 4096)
        child.flatten()
        assert child.parent_info() is None
        assert child.read(0, 4096) == before
        # chain is broken: parent snap can now be unprotected+removed
        p.snap_unprotect("base")
        p.snap_remove("base")
        assert rbd.list() == ["child", "parent"]
        rbd.remove("parent")
        assert child.read(0, 4) == b"PARE"   # survives parent removal

    def test_clone_remove_deregisters(self):
        c, io, rbd = make_rbd()
        p = self._parent_with_snap(rbd)
        rbd.clone("parent", "base", "child")
        rbd.remove("child")
        assert rbd.list_children("parent", "base") == []
        p.snap_unprotect("base")   # now legal

    def test_shrink_copy_up_boundary_piece(self):
        """A clone shrink's zero-writes can create a missing boundary
        piece; its sub-extents BELOW the new size must come from the
        parent, not become authoritative zeros."""
        c, io, rbd = make_rbd()
        p = rbd.create("parent", 4096)
        p.write(0, b"Q" * 4096)          # fully populated parent
        p.snap_create("base")
        p.snap_protect("base")
        child = rbd.clone("parent", "base", "child")
        child.write(3584, b"Z" * 512)    # only a high piece exists
        child.resize(900)                # shrink mid-piece
        assert child.read(768, 132) == b"Q" * 132

    def test_snapshot_keeps_its_own_overlap(self):
        """A later head shrink must not retroactively narrow the
        parent overlap a snapshot recorded (per-snap parent info)."""
        c, io, rbd = make_rbd()
        p = rbd.create("parent", 4096)
        p.write(0, b"W" * 4096)
        p.snap_create("base")
        p.snap_protect("base")
        child = rbd.clone("parent", "base", "child")
        child.snap_create("cs")
        child.resize(1024)               # narrows HEAD overlap only
        child.set_snap("cs")
        assert child.read(2048, 4) == b"WWWW"
        child.set_snap(None)
        child.resize(4096)
        assert child.read(2048, 4) == b"\x00" * 4

    def test_shrink_narrows_overlap(self):
        c, io, rbd = make_rbd()
        self._parent_with_snap(rbd)
        child = rbd.clone("parent", "base", "child")
        child.resize(1024)
        child.resize(4096)
        # [1024, 4096) must NOT resurrect parent bytes past overlap
        assert child.read(2048, 4) == b"\x00\x00\x00\x00"
        assert child.read(0, 12) == b"PARENT-DATA-"


class TestDiff:
    def test_diff_since_snap(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vol", 4096)
        img.write(0, b"A" * 4096)
        img.snap_create("s1")
        img.write(1024, b"B" * 100)   # dirties one 256-byte piece's object
        runs = img.diff_iterate(from_snap="s1")
        assert runs, "a write after the snap must show in the diff"
        covered = set()
        for off, ln in runs:
            covered.update(range(off, off + ln))
        assert 1024 in covered and 1100 in covered
        # most of the image is NOT in the diff
        assert len(covered) < 4096

    def test_diff_allocated_extents(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vol", 4096)
        img.write(512, b"X" * 10)
        runs = img.diff_iterate()
        covered = set()
        for off, ln in runs:
            covered.update(range(off, off + ln))
        assert 512 in covered
        assert 3500 not in covered   # untouched piece

    def test_diff_clean_image_empty(self):
        c, io, rbd = make_rbd()
        img = rbd.create("vol", 4096)
        img.write(0, b"A" * 4096)
        img.snap_create("s1")
        assert img.diff_iterate(from_snap="s1") == []


class TestExportDiff:
    """Incremental backup round-trip (ref: rbd export-diff /
    import-diff stream semantics)."""

    def test_full_then_incremental_chain(self):
        c, io, rbd = make_rbd()
        src = rbd.create("src", 4096)
        src.write(0, b"base-" * 100)
        # full export-diff -> fresh replica
        dst = rbd.create("dst", 4096)
        dst.import_diff(src.export_diff())
        assert dst.read(0, 4096) == src.read(0, 4096)
        # snapshot BOTH sides to anchor the incremental chain
        src.snap_create("s1")
        dst.snap_create("s1")
        src.write(1024, b"delta-one!" * 10)
        src.write(3000, b"tail")
        inc = src.export_diff(from_snap="s1")
        dst.import_diff(inc)
        assert dst.read(0, 4096) == src.read(0, 4096)
        # the incremental carries only changed pieces, not the image
        assert len(inc) < 4096

    def test_import_refuses_broken_chain(self):
        c, io, rbd = make_rbd()
        src = rbd.create("a", 2048)
        src.write(0, b"x" * 2048)
        src.snap_create("anchor")
        src.write(0, b"y" * 100)
        inc = src.export_diff(from_snap="anchor")
        dst = rbd.create("b", 2048)     # has NO 'anchor' snap
        with pytest.raises(KeyError, match="anchor"):
            dst.import_diff(inc)

    def test_diff_resizes_destination(self):
        c, io, rbd = make_rbd()
        src = rbd.create("grow", 1024)
        src.write(0, b"1" * 1024)
        src.snap_create("s")
        src.resize(4096)
        src.write(2048, b"2" * 512)
        dst = rbd.create("copy", 1024)
        dst.import_diff(src.export_diff())     # full, at new size
        assert dst.size() == 4096
        assert dst.read(0, 4096) == src.read(0, 4096)

    def test_full_export_of_clone_includes_parent_data(self):
        """A full export-diff of a CLONE must serialize the parent-
        inherited bytes too — the replica has no parent to fall back
        to."""
        c, io, rbd = make_rbd()
        p = rbd.create("parent", 2048)
        p.write(0, b"P" * 2048)
        p.snap_create("base")
        p.snap_protect("base")
        child = rbd.clone("parent", "base", "child")
        child.write(256, b"C" * 128)           # one child-owned piece
        dst = rbd.create("replica", 2048)
        dst.import_diff(child.export_diff())
        assert dst.read(0, 2048) == child.read(0, 2048)

    def test_export_diff_rejects_at_snap_mode(self):
        c, io, rbd = make_rbd()
        img = rbd.create("x", 1024)
        img.write(0, b"d" * 100)
        img.snap_create("s")
        img.set_snap("s")
        with pytest.raises(ValueError, match="live head"):
            img.export_diff()
