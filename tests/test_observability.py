"""Observability plane, end to end against a LIVE wire cluster.

Tier-1 smoke of ISSUE 4's acceptance surface: a real standalone
cluster (cephx + secure frames ON) is booted once per module, then

* the Unix admin socket answers `perf dump` / `dump_historic_ops` /
  `log dump` with counters from the instrumented hot paths
  (msgr / op-window / ec / cephx);
* every counter name any daemon emits was DECLARED through
  PerfCountersBuilder (catches dynamic/typo'd names in hand-assembled
  dumps);
* `ceph_cli.py --asok-dir <dir> status / health / prometheus` renders
  from MgrReport-aggregated real daemon counters, not sim-synthesized
  values;
* a seeded fault flips the SLOW_OPS and OSD_DOWN health checks.
"""

import json
import os
import time

import pytest

from ceph_tpu.utils.admin_socket import (AdminSocketError,
                                         admin_command)
from ceph_tpu.utils.perf_counters import is_declared


@pytest.fixture(scope="module")
def cluster():
    from ceph_tpu.osd.standalone import StandaloneCluster
    c = StandaloneCluster(n_osds=4, pg_num=2, cephx=True,
                          secret=os.urandom(32))
    c.wait_for_clean(timeout=40)
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def client(cluster):
    cl = cluster.client()
    objs = {f"obs-{i}": bytes([i % 251]) * (200 + i) for i in range(8)}
    cl.write(objs)
    for name in objs:
        assert cl.read(name) == objs[name]
    return cl


def _wait_for(pred, timeout, what):
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        got = pred()
        if got:
            return got
        time.sleep(0.2)
    raise TimeoutError(what)


class TestAdminSocket:
    def test_perf_dump_has_hot_path_counters(self, cluster, client):
        """`ceph daemon osd.N perf dump` over the Unix socket returns
        msgr/op-window/ec counters that actually moved under the I/O
        the client just did."""
        perf = admin_command(cluster.asok_path("osd.0"), "perf dump")
        assert perf["msgr"]["frames_tx"] > 0
        assert perf["msgr"]["frames_rx"] > 0
        assert perf["msgr"]["bytes_tx"] > 0
        # secure mode: seal/open time accumulated per frame
        assert perf["msgr"]["seal_time"]["avgcount"] > 0
        # ack coalescing: far fewer acks than frames received
        assert 0 < perf["msgr"]["acks_tx"] < perf["msgr"]["frames_rx"]
        assert perf["rpc"]["op_send"] > 0
        assert perf["cephx"]["ticket_fetches"] > 0
        # some daemon primaried a PG and encoded writes — via the
        # fused device launch OR the r13 host-encode fast path
        # (native SSE on the CPU backend), whichever served this box
        total_enc = 0
        for o in cluster.osd_ids():
            ec = admin_command(cluster.asok_path(f"osd.{o}"),
                               "perf dump")["ec"]
            total_enc += (ec["fused_write_launches"]
                          + ec["host_encode_launches"])
        assert total_enc > 0

    def test_every_emitted_counter_was_declared(self, cluster, client):
        """The declared-name invariant: every (logger, key) a daemon's
        perf dump emits exists in the PerfCountersBuilder registry —
        a hand-assembled/typo'd counter name fails here."""
        for osd in cluster.osd_ids():
            perf = admin_command(cluster.asok_path(f"osd.{osd}"),
                                 "perf dump")
            for logger, counters in perf.items():
                for key in counters:
                    assert is_declared(logger, key), \
                        f"{logger}.{key} emitted but never declared"
        mon_perf = admin_command(cluster.asok_path("mon.0"),
                                 "perf dump")["mon.0"]
        for logger, counters in mon_perf.items():
            for key in counters:
                assert is_declared(logger, key), \
                    f"mon {logger}.{key} emitted but never declared"

    def test_historic_ops_and_log_dump(self, cluster, client):
        p = cluster.asok_path("osd.0")
        # some osd served client ops; find one with history
        hists = [admin_command(cluster.asok_path(f"osd.{o}"),
                               "dump_historic_ops")
                 for o in cluster.osd_ids()]
        assert any(h["num_ops"] > 0 for h in hists)
        busy = next(h for h in hists if h["num_ops"] > 0)
        events = [e["event"] for e in
                  busy["ops"][0]["type_data"]["events"]]
        assert "reached_pg" in events and "done" in events
        lines = admin_command(p, "log dump")["lines"]
        assert isinstance(lines, list)
        assert admin_command(p, "dump_ops_in_flight")["num_ops"] == 0
        assert "complaint_time" in admin_command(p, "slow_ops")

    def test_perf_schema_reset_help_unknown(self, cluster, client):
        p = cluster.asok_path("osd.1")
        schema = admin_command(p, "perf schema")
        assert schema["msgr"]["frames_tx"]["kind"] == "counter"
        assert schema["msgr"]["seal_time"]["kind"] == "time_avg"
        helps = admin_command(p, "help")
        assert "perf dump" in helps and "log dump" in helps
        before = admin_command(p, "perf dump")["msgr"]["frames_tx"]
        assert admin_command(p, "perf reset") == {"success": True}
        after = admin_command(p, "perf dump")["msgr"]["frames_tx"]
        # heartbeats keep ticking between reset and dump, so "less
        # than the whole boot history" is the stable claim
        assert after < before
        with pytest.raises(AdminSocketError, match="unknown command"):
            admin_command(p, "definitely not a command")

    def test_wire_admin_op_same_dispatcher(self, cluster, client):
        """The legacy wire `admin` MOSDOp serves the SAME extended
        command set (one dispatcher, two surfaces)."""
        out = client.daemon(2, "config show")
        assert "osd_op_complaint_time" in out
        perf = client.daemon(2, "perf dump")
        assert "msgr" in perf and "rpc" in perf


class TestMgrAggregation:
    def test_status_health_from_real_reports(self, cluster, client):
        """`ceph status` renders from MgrReport-aggregated daemon
        counters: every OSD + at least one mon reporting, PGs
        active+clean, HEALTH_OK."""
        st = _wait_for(
            lambda: (s := client.status())["daemons_reporting"]
            >= cluster.n_osds + 1 and s["health"] == "HEALTH_OK"
            and s,
            30, "all daemons reporting + HEALTH_OK")
        assert st["osds_up"] == cluster.n_osds
        assert st["pg_states"].get("active+clean") == cluster.pg_num
        assert st["mon_leader"] == 0
        h = client.health(detail=True)
        assert h["status"] == "HEALTH_OK" and h["checks"] == []

    def test_prometheus_from_aggregated_counters(self, cluster,
                                                 client):
        text = _wait_for(
            lambda: (t := client.prometheus_text())
            and 'ceph_tpu_osd_op{daemon="osd.' in t and t,
            30, "osd counters in exposition")
        # per-daemon labels over the REAL counters
        assert '# TYPE ceph_tpu_msgr_frames_tx counter' in text
        assert 'ceph_tpu_rpc_op_send{daemon=' in text
        assert 'ceph_tpu_mon_' in text          # control plane too
        # every sample line parses as name{labels} value
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                assert len(line.rsplit(" ", 1)) == 2, line
        # and the op counter really carries the I/O we did
        total_op = sum(
            float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("ceph_tpu_osd_op{"))
        assert total_op >= 8                    # the writes + reads

    def test_ceph_cli_live_mode(self, cluster, client, capsys):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        os.pardir, "tools"))
        import ceph_cli
        _wait_for(lambda: client.status()["daemons_reporting"]
                  >= cluster.n_osds, 30, "daemons reporting")
        ceph_cli.main(["--asok-dir", cluster.admin_dir, "status"])
        out = capsys.readouterr().out
        assert "health:" in out and "osd:" in out
        ceph_cli.main(["--asok-dir", cluster.admin_dir, "--json",
                       "health", "detail"])
        h = json.loads(capsys.readouterr().out)
        assert h["status"] in ("HEALTH_OK", "HEALTH_WARN")
        ceph_cli.main(["--asok-dir", cluster.admin_dir, "prometheus"])
        assert "ceph_tpu_osd_op{" in capsys.readouterr().out
        ceph_cli.main(["--asok-dir", cluster.admin_dir, "--json",
                       "daemon", "osd.0", "perf", "dump"])
        perf = json.loads(capsys.readouterr().out)
        assert "msgr" in perf


class TestChaosLogRouting:
    def test_thrasher_events_land_in_log_ring(self):
        """Thrasher events ride `dout("chaos", ...)` with the seed in
        every line, so `log dump` over any admin socket reconstructs
        the fault timeline (gathered, not printed)."""
        from ceph_tpu.chaos.thrasher import Thrasher
        from ceph_tpu.utils.log import g_log
        th = Thrasher(seed=4242)          # no cluster boot needed
        th._log("kill osd.1")
        th._log("revive osd.1")
        lines = [ln for ln in g_log.dump_recent()
                 if "thrash seed=4242" in ln]
        assert any("kill osd.1" in ln for ln in lines)
        assert any("revive osd.1" in ln for ln in lines)
        # events were gathered, not printed (chaos log level is 0)
        assert th.schedule == ["kill osd.1", "revive osd.1"]


class TestHealthFlips:
    def test_slow_ops_flip(self, cluster, client):
        """SLOW_OPS: a config-tuned complaint time + a genuinely
        in-flight op flips the check through the REAL report path
        (daemon OpTracker -> MgrReport -> monitor health)."""
        client.config_set("osd_op_complaint_time", 0.05, timeout=20)
        d = cluster.osds[0]
        op = d.op_tracker.create_op("wedged op (test)")
        try:
            h = _wait_for(
                lambda: (hh := client.health(detail=True))
                and any(c["code"] == "SLOW_OPS"
                        for c in hh["checks"]) and hh,
                30, "SLOW_OPS raised")
            slow = next(c for c in h["checks"]
                        if c["code"] == "SLOW_OPS")
            assert any("osd.0" in line for line in slow["detail"])
        finally:
            op.finish()
            client.config_rm("osd_op_complaint_time", timeout=20)
        _wait_for(
            lambda: not any(c["code"] == "SLOW_OPS"
                            for c in client.health()["checks"]),
            30, "SLOW_OPS cleared")

    def test_osd_down_flip(self, cluster, client):
        """OSD_DOWN: a killed daemon flips health through the real
        failure-detection path, and the check clears on revive."""
        victim = 3
        cluster.kill_osd(victim)
        try:
            cluster.wait_for_down(victim, timeout=40)
            h = _wait_for(
                lambda: (hh := client.health(detail=True))
                and any(c["code"] == "OSD_DOWN"
                        for c in hh["checks"]) and hh,
                30, "OSD_DOWN raised")
            down = next(c for c in h["checks"]
                        if c["code"] == "OSD_DOWN")
            assert f"osd.{victim} is down" in down["detail"]
        finally:
            cluster.revive_osd(victim)
        _wait_for(
            lambda: client.status()["osds_up"] == cluster.n_osds,
            40, "revived osd back up")
        cluster.wait_for_clean(timeout=40)
